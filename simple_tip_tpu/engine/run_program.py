"""AOT run programs: compile the whole per-run chain once, cache it on disk.

``ops/fused_chain.py`` provides the pure chain (predict -> quantify ->
profile-pack) and rank (greedy CAM) functions; this module is the engine
layer that AOT-compiles them per (case-study, model-group, badge-shape),
keeps the compiled executables in a ``ProgramCache`` keyed by
SAFitCache-style content fingerprints (module hash + shapes + dtype +
backend), and drives the badge walk for ``eval_prioritization`` behind
``TIP_FUSED_CHAIN=1``. The per-phase path stays untouched as the
seeded-parity reference.

Why AOT (``jax.jit(...).lower(specs).compile()``) instead of plain jit:

- compile time is OBSERVED, not ambushed: it lands in the
  ``run_program.compile`` obs span instead of silently inflating the first
  badge's latency;
- the compiled executable can be serialized
  (``jax.experimental.serialize_executable``) and reused by the NEXT
  scheduler process — run_scheduler spawns a fresh interpreter per phase,
  so without the disk cache every worker would recompile the same chain;
- the input signature is pinned: every badge is padded to ONE shape (the
  traced ``valid`` scalar masks the padding), so a dataset's ragged tail
  can never retrace — the failure mode tiplint's ``retrace-risk`` rule
  guards against.

Env knobs: ``TIP_FUSED_CHAIN`` (off by default), ``TIP_PROGRAM_CACHE_DIR``
(``off``/``0`` disables; default ``$TIP_ASSETS/program_cache``),
``TIP_PROGRAM_CACHE_MAX_BYTES`` (LRU sweep, same grammar as
``TIP_SA_CACHE_MAX_BYTES``), ``TIP_INT8_PROFILES`` (exact int8 coverage
coding, see ops/fused_chain.py).
"""

import contextlib
import hashlib
import logging
import os
import pickle
from typing import Dict, Optional, Tuple

import numpy as np

from simple_tip_tpu import obs
from simple_tip_tpu.obs import devicemeter
from simple_tip_tpu.ops.timer import Timer
from simple_tip_tpu.utils.artifacts_io import atomic_write_bytes

logger = logging.getLogger(__name__)

#: Memoized (platform, device_kind, device count) for dispatch grading —
#: resolved once per process, after the first program is in hand (so the
#: backend is already initialized and the query is free).
_device_info_cache = None


def _device_info():
    global _device_info_cache
    if _device_info_cache is None:
        _device_info_cache = devicemeter.detect_device()
    return _device_info_cache


def _observe_dispatch(program: str, dt_s: float) -> None:
    """Grade one measured dispatch against the program's analytic cost
    (devicemeter registry; stamped at compile, recovered on cache hit)."""
    platform, kind, cores = _device_info()
    devicemeter.observe_dispatch(
        program, dt_s, platform=platform, device_kind=kind, cores=cores
    )

#: Bump when the chain/rank program semantics or the entry layout change;
#: stale-version entries are treated as misses.
PROGRAM_FORMAT_VERSION = "run-program-v1"


def fused_chain_enabled() -> bool:
    """True when ``TIP_FUSED_CHAIN`` opts the prio path into fused dispatch."""
    return os.environ.get("TIP_FUSED_CHAIN", "").strip().lower() in (
        "1",
        "on",
        "true",
    )


def int8_profiles_enabled() -> bool:
    """True when ``TIP_INT8_PROFILES`` opts into the exact int8 coding."""
    return os.environ.get("TIP_INT8_PROFILES", "").strip().lower() in (
        "1",
        "on",
        "true",
    )


def chain_group_size() -> int:
    """Cross-run dispatch fusion group size from ``TIP_CHAIN_GROUP``.

    The number of models scored per chain dispatch: the study walks the
    same test inputs across R independently trained runs, so grouping G of
    them into one vmapped dispatch turns R dispatches per badge into
    ceil(R/G) (``GroupChainRunner``). Empty / ``0`` / ``off`` / ``1`` means
    ungrouped (the per-model ``FusedChainRunner`` walk).
    """
    raw = os.environ.get("TIP_CHAIN_GROUP", "").strip().lower()
    if not raw or raw == "off":
        return 1
    try:
        g = int(raw)
    except ValueError:
        raise ValueError(
            f"TIP_CHAIN_GROUP={raw!r} not recognized "
            "(positive integer group size, or off)"
        )
    return max(g, 1)


def program_cache_max_bytes() -> Optional[int]:
    """Size cap from ``TIP_PROGRAM_CACHE_MAX_BYTES`` (same grammar as
    ``TIP_SA_CACHE_MAX_BYTES``: plain bytes or k/m/g suffix; empty / ``0``
    / ``off`` / ``unlimited`` / ``none`` means uncapped)."""
    raw = os.environ.get("TIP_PROGRAM_CACHE_MAX_BYTES", "").strip().lower()
    if not raw or raw in ("0", "off", "unlimited", "none"):
        return None
    mult = 1
    if raw[-1] in ("k", "m", "g"):
        mult = {"k": 1024, "m": 1024**2, "g": 1024**3}[raw[-1]]
        raw = raw[:-1]
    try:
        return int(float(raw) * mult)
    except ValueError:
        raise ValueError(
            f"TIP_PROGRAM_CACHE_MAX_BYTES={raw!r} not recognized "
            "(bytes, or k/m/g suffix)"
        )


def _metric_signature(metric) -> str:
    """Content hash of one coverage metric's configuration (thresholds are
    BAKED into the compiled program as constants, so they must key it)."""
    h = hashlib.sha256()
    h.update(type(metric).__name__.encode())
    for k in sorted(vars(metric)):
        v = vars(metric)[k]
        h.update(k.encode())
        if isinstance(v, np.ndarray):
            h.update(str(v.shape).encode() + str(v.dtype).encode())
            h.update(np.ascontiguousarray(v).tobytes())
        else:
            h.update(repr(v).encode())
    return h.hexdigest()


def program_fingerprint(
    model_def, params, layer_ids, metrics: Dict, x_shape, x_dtype, *tags
) -> str:
    """SAFitCache-style fingerprint of one compiled chain program.

    Covers everything the lowered program depends on: format version, the
    flax module config (``repr`` — flax modules render their full config),
    tap layer ids, every metric's baked threshold content, the parameter
    tree's shapes/dtypes (values are runtime inputs, NOT baked), the badge
    shape/dtype, the backend, and the jax version (serialized executables
    are not portable across either).
    """
    import jax

    h = hashlib.sha256()
    h.update(PROGRAM_FORMAT_VERSION.encode())
    h.update(repr(model_def).encode())
    h.update(repr(list(layer_ids)).encode())
    for mid in sorted(metrics):
        h.update(mid.encode())
        h.update(_metric_signature(metrics[mid]).encode())
    for leaf in jax.tree_util.tree_leaves(params):
        h.update(str(np.shape(leaf)).encode())
        h.update(str(getattr(leaf, "dtype", type(leaf).__name__)).encode())
    h.update(str(tuple(x_shape)).encode() + str(x_dtype).encode())
    h.update(jax.default_backend().encode())
    h.update(jax.__version__.encode())
    for tag in tags:
        h.update(str(tag).encode())
    return h.hexdigest()


def select_fingerprint(n: int, k: int, *tags) -> str:
    """Fingerprint of one AL top-k select program — pure shape-keyed."""
    import jax

    h = hashlib.sha256()
    h.update(PROGRAM_FORMAT_VERSION.encode())
    h.update(f"select:{n}topk{k}".encode())
    h.update(jax.default_backend().encode())
    h.update(jax.__version__.encode())
    for tag in tags:
        h.update(str(tag).encode())
    return h.hexdigest()


def rank_fingerprint(num_badges: int, badge: int, words: int, *tags) -> str:
    """Fingerprint of one rank (greedy CAM) program — pure shape-keyed."""
    import jax

    h = hashlib.sha256()
    h.update(PROGRAM_FORMAT_VERSION.encode())
    h.update(f"rank:{num_badges}x{badge}x{words}".encode())
    h.update(jax.default_backend().encode())
    h.update(jax.__version__.encode())
    for tag in tags:
        h.update(str(tag).encode())
    return h.hexdigest()


class ProgramCache:
    """Disk cache of serialized AOT executables, one pickle per program.

    Mirrors ``SAFitCache``'s semantics: atomic writes so concurrent
    scheduler workers can share one dir, meta verified on load, ANY
    read/deserialize failure degrading to a recompile (a corrupt cache can
    cost time, never correctness), ``os.utime`` on hit for LRU recency,
    and an ``TIP_PROGRAM_CACHE_MAX_BYTES`` sweep that never evicts the
    just-written entry.
    """

    def __init__(self, root: str):
        self.root = root
        from simple_tip_tpu.utils.artifacts_io import sweep_orphan_tmp

        sweep_orphan_tmp(self.root)

    @classmethod
    def from_env(cls) -> Optional["ProgramCache"]:
        """Cache handle per ``TIP_PROGRAM_CACHE_DIR`` policy, or None when
        off (``off``/``0``; default ``$TIP_ASSETS/program_cache``)."""
        raw = os.environ.get("TIP_PROGRAM_CACHE_DIR", "").strip()
        if raw.lower() in ("off", "0"):
            return None
        if not raw:
            from simple_tip_tpu.config import output_folder

            raw = os.path.join(output_folder(), "program_cache")
        return cls(root=raw)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"prog_{key[:24]}.pkl")

    def load(self, key: str):
        """The cached compiled executable, or None on miss/stale/corrupt."""
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                entry = pickle.load(f)
            meta = entry["meta"]
            if (
                meta["version"] != PROGRAM_FORMAT_VERSION
                or meta["fingerprint"] != key
            ):
                logger.info("program cache STALE (%s)", path)
                obs.counter("program_cache.stale").inc()
                obs.event("program_cache", outcome="stale")
                return None
            from jax.experimental import serialize_executable

            compiled = serialize_executable.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"]
            )
            obs.counter("program_cache.hit").inc()
            obs.event("program_cache", outcome="hit", program=meta.get("program"))
            # cost_analysis() can fail on deserialized executables, so the
            # compile-time cost stamped into the meta is the recovery path
            # for dispatch grading on a warm cache
            if meta.get("cost"):
                devicemeter.record_program_cost(
                    meta.get("program") or "", meta["cost"], fingerprint=key[:16]
                )
            try:
                os.utime(path)  # LRU recency: a hit entry is the last swept
            except OSError:
                pass
            return compiled
        except FileNotFoundError:
            obs.counter("program_cache.miss").inc()
            obs.event("program_cache", outcome="miss")
            return None
        except Exception as e:  # noqa: BLE001 — any bad entry degrades to recompile
            logger.warning(
                "program cache entry corrupt (%s: %r); recompiling", path, e
            )
            obs.counter("program_cache.corrupt").inc()
            obs.event("program_cache", outcome="corrupt")
            return None

    def store(self, key: str, compiled, program: str = "", cost=None) -> None:
        """Persist one compiled executable (atomic; failures warn, never
        raise — the cache is an optimization only). ``cost`` is the
        compile-time ``cost_analysis()`` stamp, advisory fingerprint-adjacent
        metadata: entries without it (older caches) just skip dispatch
        grading, so no format-version bump."""
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(compiled)
            os.makedirs(self.root, exist_ok=True)
            entry = {
                "meta": {
                    "version": PROGRAM_FORMAT_VERSION,
                    "fingerprint": key,
                    "program": program,
                    **({"cost": cost} if cost else {}),
                },
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            }
            path = self._path(key)
            atomic_write_bytes(path, pickle.dumps(entry, protocol=4))
            logger.info("program cache stored %s (%s)", program, path)
            obs.counter("program_cache.store").inc()
            self._sweep(keep=path)
        except Exception as e:  # noqa: BLE001 — cache is an optimization only
            logger.warning("program cache store failed (%r)", e)

    def _sweep(self, keep: str) -> None:
        """Evict least-recently-used entries until the dir fits the cap
        (never the just-written ``keep`` entry)."""
        cap = program_cache_max_bytes()
        if cap is None:
            return
        entries = []
        for name in os.listdir(self.root):
            if not name.endswith(".pkl"):
                continue
            full = os.path.join(self.root, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, full))
        total = sum(size for _, size, _ in entries)
        keep = os.path.abspath(keep)
        for _, size, full in sorted(entries):
            if total <= cap:
                break
            if os.path.abspath(full) == keep:
                continue
            try:
                os.unlink(full)
            except OSError:
                continue
            total -= size
            logger.info("program cache evicted %s (cap %d bytes)", full, cap)
            obs.counter("program_cache.evict").inc()
            obs.event("program_cache", outcome="evict", path=full)


@contextlib.contextmanager
def _fresh_backend_compile():
    """Force a real backend compile (skip jax's persistent compilation
    cache). Executables RESTORED from the persistent cache serialize an
    incomplete payload on CPU — ``deserialize_and_load`` later fails with
    "Symbols not found" — so a program destined for the ProgramCache must
    come from an actual compile. The ProgramCache then replaces the
    persistent cache's role for these programs.

    Toggling ``jax_enable_compilation_cache`` alone is not enough:
    ``compilation_cache.is_cache_used`` memoizes its verdict at the first
    compile of the process, so the memo must be reset on both sides of the
    toggle (reset_cache only drops the in-memory LRU; the disk cache is
    untouched). Private-API drift degrades to the plain compile — worst
    case is today's behavior (corrupt entry -> recompile), never an error."""
    import jax

    try:
        from jax._src import compilation_cache as _cc
    except Exception:  # pragma: no cover - jax internals moved
        _cc = None
    prev = jax.config.jax_enable_compilation_cache
    try:
        jax.config.update("jax_enable_compilation_cache", False)
        if _cc is not None:
            _cc.reset_cache()
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)
        if _cc is not None:
            _cc.reset_cache()


def aot_compile(jitted, arg_specs, cache: Optional[ProgramCache], key: str, program: str):
    """Cache-backed ``jitted.lower(*specs).compile()`` with the compile time
    stamped into a ``run_program.compile`` obs span."""
    compiled = cache.load(key) if cache is not None else None
    with obs.span("run_program.compile", program=program) as sp:
        if compiled is not None:
            sp.set(cached=True, fingerprint=key[:16])
            return compiled
        timer = Timer()
        with timer:
            if cache is not None:
                with _fresh_backend_compile():
                    compiled = jitted.lower(*arg_specs).compile()
            else:
                compiled = jitted.lower(*arg_specs).compile()
        # analytic cost accounting: only a FRESH compile reliably answers
        # cost_analysis(), so this is the one place the stamp can be made
        cost = devicemeter.extract_cost(compiled)
        devicemeter.record_program_cost(program, cost, fingerprint=key[:16])
        sp.set(cached=False, compile_s=round(timer.get(), 6), fingerprint=key[:16])
        if cost:
            sp.set(
                cost_flops=cost.get("flops"),
                cost_bytes=cost.get("bytes_accessed"),
            )
    if cache is not None:
        cache.store(key, compiled, program=program, cost=cost)
    return compiled


def _donate(*argnums) -> Tuple[int, ...]:
    """Donation argnums, disabled on CPU where XLA ignores donation and
    warns per call (TPU/GPU reuse the donated buffers — the SNIPPETS.md [3]
    compile_step pattern)."""
    import jax

    return tuple(argnums) if jax.default_backend() != "cpu" else ()


class FusedChainRunner:
    """One model's whole-chain fused prio evaluation.

    Owns a ``CoverageWorker`` purely for its configured metrics, train-stats
    pass (shared via ``CoverageStatsCache``) and per-metric setup debits —
    the thresholds baked into the chain program are byte-identical to the
    per-phase path's. Compiles ONE chain program (badge-shaped, padded) and
    one rank program per distinct packed word width, both through the
    ``ProgramCache``.

    ``group_params`` (optional, stacked [G, ...] parameter tree) switches
    the chain to the vmapped G-run ensemble-group form; scores/orders are
    then returned per group member.
    """

    def __init__(
        self,
        model_def,
        params,
        training_set: np.ndarray,
        nc_layers,
        batch_size: int = 32,
        badge_size: Optional[int] = None,
        cache: Optional[ProgramCache] = "env",
        in_shardings=None,
        out_shardings=None,
    ):
        import jax

        from simple_tip_tpu.engine.coverage_handler import (
            PROFILE_BADGE_SIZE,
            CoverageWorker,
        )
        from simple_tip_tpu.engine.model_handler import BaseModel
        from simple_tip_tpu.ops.fused_chain import make_chain_fn, rank_badges

        self.model_def = model_def
        self.params = params
        self.batch_size = batch_size
        self.badge_size = badge_size or PROFILE_BADGE_SIZE
        self.layer_ids = tuple(i for i in nc_layers if isinstance(i, int))
        self.int8 = int8_profiles_enabled()
        self.cache = ProgramCache.from_env() if cache == "env" else cache
        self.worker = CoverageWorker(
            base_model=BaseModel(
                model_def, params, activation_layers=nc_layers, batch_size=batch_size
            ),
            training_set=training_set,
        )
        chain = make_chain_fn(
            model_def,
            self.layer_ids,
            self.worker.metrics,
            int8_profiles=self.int8,
        )
        jit_kwargs = {}
        if in_shardings is not None:
            jit_kwargs["in_shardings"] = in_shardings
        if out_shardings is not None:
            jit_kwargs["out_shardings"] = out_shardings
        # donate the badge buffer: each walk step uploads a fresh badge, so
        # the previous one's device memory is reusable by the program
        self._chain_jit = jax.jit(chain, donate_argnums=_donate(1), **jit_kwargs)
        self._rank_jit = jax.jit(rank_badges, donate_argnums=_donate(0))
        self._chain_compiled = {}  # (shape, dtype) -> executable
        self._rank_compiled = {}  # (num_badges, words) -> executable
        self._select_compiled = {}  # (n, k) -> executable

    # -- program resolution --------------------------------------------------

    def _chain_program(self, x_shape, x_dtype):
        import jax

        key = (tuple(x_shape), str(x_dtype))
        prog = self._chain_compiled.get(key)
        if prog is None:
            fp = program_fingerprint(
                self.model_def,
                self.params,
                self.layer_ids,
                self.worker.metrics,
                x_shape,
                x_dtype,
                "chain",
                f"int8={self.int8}",
            )
            param_specs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), self.params
            )
            x_spec = jax.ShapeDtypeStruct(tuple(x_shape), x_dtype)
            valid_spec = jax.ShapeDtypeStruct((), np.dtype(np.int32))
            prog = aot_compile(
                self._chain_jit,
                (param_specs, x_spec, valid_spec),
                self.cache,
                fp,
                program="chain",
            )
            self._chain_compiled[key] = prog
        return prog

    def chain_program(self, x_shape, x_dtype):
        """The AOT chain executable for one badge shape (public warm-pool
        entry: the serving executor resolves programs at model-register
        time through this, so a request never pays a compile)."""
        return self._chain_program(x_shape, x_dtype)

    def select_program(self, n: int, k: int):
        """The AOT AL top-k select executable over an [n]-vector (public
        counterpart of ``chain_program`` for the select step)."""
        return self._select_program(n, k)

    def _rank_program(self, num_badges: int, words: int):
        import jax

        key = (num_badges, words)
        prog = self._rank_compiled.get(key)
        if prog is None:
            fp = rank_fingerprint(num_badges, self.badge_size, words)
            spec = tuple(
                jax.ShapeDtypeStruct((self.badge_size, words), np.dtype(np.uint32))
                for _ in range(num_badges)
            )
            prog = aot_compile(
                self._rank_jit, (spec,), self.cache, fp, program="rank"
            )
            self._rank_compiled[key] = prog
        return prog

    def _select_program(self, n: int, k: int):
        import jax

        from simple_tip_tpu.ops.fused_chain import make_select_fn

        key = (int(n), int(k))
        prog = self._select_compiled.get(key)
        if prog is None:
            fp = select_fingerprint(n, k)
            spec = (
                jax.ShapeDtypeStruct((int(n),), np.dtype(np.float32)),
                jax.ShapeDtypeStruct((), np.dtype(np.int32)),
            )
            prog = aot_compile(
                jax.jit(make_select_fn(int(k))),
                spec,
                self.cache,
                fp,
                program="select",
            )
            self._select_compiled[key] = prog
        return prog

    def select_top_k(self, values: np.ndarray, k: int) -> np.ndarray:
        """AL top-k select of one host [n] score vector via the AOT select
        program (padded to the badge-aligned shape so repeated selects of
        ragged dataset sizes share one executable).

        Returns the selected indices ascending by value, best-last —
        byte-identical to ``np.argsort(values, kind="stable")[-k:]``, the
        semantics ``eval_active_learning`` applies on host.
        """
        values = np.asarray(values, np.float32)
        n = values.shape[0]
        if not 0 < k <= n:
            raise ValueError(f"select_top_k: k={k} outside 1..{n}")
        padded_n = -(-n // self.badge_size) * self.badge_size
        if padded_n > n:
            values = np.concatenate([values, np.zeros(padded_n - n, np.float32)])
        prog = self._select_program(padded_n, k)
        timer = Timer()
        with timer:
            picked = prog(values, np.int32(n))
        obs.counter("run_program.select_dispatches").inc()
        _observe_dispatch("select", timer.get())
        return np.asarray(picked).astype(np.int64)

    # -- evaluation ----------------------------------------------------------

    def evaluate_dataset(self, x: np.ndarray, rng=None, select_k=None) -> Dict:
        """Fused prio evaluation of one test set.

        Returns a dict with ``pred`` (host [n]), ``uncertainties`` /
        ``unc_times``, per-metric ``scores`` / ``cam_orders`` /
        ``cov_times`` — value- and contract-compatible with what the
        per-phase ``_eval_fault_predictors`` + ``CoverageWorker`` pair
        produces, from 1 chain dispatch per badge + 1 rank dispatch per
        metric instead of one program per (phase, metric, badge shape).
        ``select_k`` additionally folds the AL top-k pick into the program
        pipeline: the result gains ``al_select`` ({quantifier: indices of
        the k most uncertain inputs, ascending by value, best-last}).
        """
        from simple_tip_tpu.ops.prioritizers import _with_score_tail

        n = int(x.shape[0])
        bs = self.badge_size
        x = np.asarray(x)
        prog = self._chain_program((bs,) + x.shape[1:], x.dtype)

        preds, unc_acc, score_acc = [], {}, {}
        packed_acc: Dict[str, list] = {m: [] for m in self.worker.metrics}
        chain_s = 0.0
        for start in range(0, n, bs):
            xb = x[start : start + bs]
            valid = xb.shape[0]
            if valid < bs:
                xb = np.concatenate(
                    [xb, np.zeros((bs - valid,) + x.shape[1:], x.dtype)]
                )
            timer = Timer()
            with timer:
                pred_b, unc_b, cov_b = prog(
                    self.params, xb, np.int32(valid)
                )
                obs.counter("run_program.chain_dispatches").inc()
                # small outputs cross to host per badge (bytes/input);
                # the packed profile matrices STAY on device for the rank
                # program — the whole point of the fused chain
                preds.append(np.asarray(pred_b)[:valid])
                for name, u in unc_b.items():
                    unc_acc.setdefault(name, []).append(np.asarray(u)[:valid])
                for mid, (s, p) in cov_b.items():
                    score_acc.setdefault(mid, []).append(np.asarray(s)[:valid])
                    packed_acc[mid].append(p)
            chain_s += timer.get()
            _observe_dispatch("chain", timer.get())

        pred = np.concatenate(preds, axis=0)
        uncertainties = {k: np.concatenate(v, axis=0) for k, v in unc_acc.items()}
        scores = {k: np.concatenate(v, axis=0) for k, v in score_acc.items()}

        # the one fused dispatch covers predict AND quantify; record its
        # full wall-clock as the shared prediction time (the same
        # shared-pred accounting the per-phase path uses) with a zero
        # quantify entry — the sum stays honest
        unc_times = {name: [0, chain_s, 0.0, 0] for name in uncertainties}
        cov_times = {
            mid: [self.worker.setup_times[mid], chain_s, 0.0]
            for mid in self.worker.metrics
        }

        cam_orders = {}
        for mid in self.worker.metrics:
            badges = packed_acc[mid]
            words = int(badges[0].shape[1])
            rank_prog = self._rank_program(len(badges), words)
            timer = Timer(name="run_program.rank", metric=mid)
            with timer:
                picked_dev, count_dev = rank_prog(tuple(badges))
                obs.counter("run_program.rank_dispatches").inc()
                count = int(count_dev)
                picked = np.asarray(picked_dev)[:count].astype(np.int64)
                order = _with_score_tail(scores[mid], picked)
            cov_times[mid].append(timer.get())
            _observe_dispatch("rank", timer.get())
            cam_orders[mid] = order
            self._sanity_check(order, scores[mid])
        if rng is not None and getattr(self.model_def, "has_dropout", False):
            self._add_variation_ratio(x, rng, uncertainties, unc_times)
        al_select = None
        if select_k:
            # the AL-select fold (ROADMAP raw-speed (b) remainder): the
            # top-k pick every quantifier's AL loop would do on host runs
            # as one more cached AOT program per (padded n, k)
            al_select = {
                name: self.select_top_k(u, int(select_k))
                for name, u in uncertainties.items()
            }
        return {
            "pred": pred,
            "uncertainties": uncertainties,
            "unc_times": unc_times,
            "scores": scores,
            "cam_orders": cam_orders,
            "cov_times": cov_times,
            **({"al_select": al_select} if al_select is not None else {}),
        }

    def _add_variation_ratio(self, x, rng, uncertainties, unc_times):
        """MC-dropout VR exactly as the per-phase path computes it (same
        vote function, same rng, same batch policy) — the stochastic pass
        cannot fuse into the deterministic chain program, so it rides the
        existing scanned-votes dispatch."""
        from simple_tip_tpu.engine.model_handler import DROPOUT_SAMPLE_SIZE
        from simple_tip_tpu.models.train import mc_dropout_votes

        sampling_timer = Timer()
        with sampling_timer:
            counts = mc_dropout_votes(
                self.model_def,
                self.params,
                x,
                n_samples=DROPOUT_SAMPLE_SIZE,
                rng=rng,
                batch_size=max(self.batch_size, 128),
            )
        quant_timer = Timer()
        with quant_timer:
            majority_count = counts.max(axis=1)
            vr = 1.0 - majority_count / DROPOUT_SAMPLE_SIZE
        uncertainties["VR"] = vr
        unc_times["VR"] = [0, sampling_timer.get(), quant_timer.get(), 0]

    @staticmethod
    def _sanity_check(order, scores):
        assert (
            len(order) == len(set(int(i) for i in order)) == scores.shape[0]
        ), "CAM order is not unique or not complete"


class GroupChainRunner:
    """G models' whole-chain prio evaluation in ONE dispatch per badge.

    The study shape is R independently trained runs walked over the SAME
    test inputs; the per-model ``FusedChainRunner`` still pays one chain
    dispatch per badge per model. This runner stacks G member checkpoints
    into one pytree (``parallel/ensemble.stack_params`` — the layout
    ``train_ensemble`` already proved) and scores a badge for all G members
    with one vmapped dispatch, so R runs cost ceil(R/G) dispatches per
    badge instead of R.

    Per-member threshold statistics (NBC/SNAC/KMNC boundaries come from
    each member's OWN training activations) ride as traced inputs — the
    stacked ``ThresholdCodebook.table`` triple — so one compiled program
    serves every member and every group of the same shape; see
    ``ops/fused_chain.make_member_chain_fn``. A ragged final group
    (``len(members) < group_size``) is padded by repeating member 0 with a
    traced member-valid scalar zeroing the padding members' packed
    profiles, so the tail reuses the same compiled shape.

    ``evaluate_dataset`` returns ONE result dict per real member, each
    contract-identical to ``FusedChainRunner.evaluate_dataset`` — the
    fan-out that keeps ``eval_prioritization``'s per-model artifacts
    byte-identical to the per-model walk (parity-pinned in tests and
    ``scripts/fused_chain_smoke.py``).
    """

    def __init__(
        self,
        model_def,
        params_list,
        training_set: np.ndarray,
        nc_layers,
        batch_size: int = 32,
        badge_size: Optional[int] = None,
        cache: Optional[ProgramCache] = "env",
        group_size: Optional[int] = None,
        staged_params=None,
    ):
        import jax

        from simple_tip_tpu.engine.coverage_handler import (
            PROFILE_BADGE_SIZE,
            CoverageWorker,
        )
        from simple_tip_tpu.engine.model_handler import BaseModel
        from simple_tip_tpu.ops.fused_chain import (
            ThresholdCodebook,
            make_group_chain_fn,
            rank_badges_grouped,
        )

        if not params_list:
            raise ValueError("GroupChainRunner needs at least one member")
        self.model_def = model_def
        self.params_list = list(params_list)
        self.n_members = len(self.params_list)
        self.group_size = int(group_size or self.n_members)
        if self.n_members > self.group_size:
            raise ValueError(
                f"{self.n_members} members exceed group_size={self.group_size}"
            )
        self.batch_size = batch_size
        self.badge_size = badge_size or PROFILE_BADGE_SIZE
        self.layer_ids = tuple(i for i in nc_layers if isinstance(i, int))
        self.cache = ProgramCache.from_env() if cache == "env" else cache

        # One CoverageWorker per member: each member's thresholds come from
        # ITS training-stats pass (shared via CoverageStatsCache), exactly
        # as the per-model walk computes them — the parity precondition.
        self.workers = [
            CoverageWorker(
                base_model=BaseModel(
                    model_def, p, activation_layers=nc_layers, batch_size=batch_size
                ),
                training_set=training_set,
            )
            for p in self.params_list
        ]
        self._codebooks = [ThresholdCodebook(w.metrics) for w in self.workers]
        sig0 = self._codebooks[0].spec_signature()
        for g, cb in enumerate(self._codebooks[1:], start=1):
            if cb.spec_signature() != sig0:
                raise ValueError(
                    f"member {g} metric structure differs from member 0; "
                    "group members must share metric configuration"
                )
        self._spec_sig = hashlib.sha256(repr(sig0).encode()).hexdigest()
        self.metrics = self.workers[0].metrics

        self.stacked_params = (
            staged_params
            if staged_params is not None
            else self.stage(self.params_list, self.group_size)
        )

        group_chain = make_group_chain_fn(
            model_def, self.layer_ids, self.metrics, member_tables=True
        )
        # donate the badge buffer (arg 2); the stacked weights and tables
        # STAY device-resident across the whole walk
        self._group_jit = jax.jit(group_chain, donate_argnums=_donate(2))
        self._grank_jit = jax.jit(rank_badges_grouped, donate_argnums=_donate(0))
        self._tables = {}  # n_neurons -> stacked (vals, strict, rank)
        self._chain_compiled = {}  # (shape, dtype) -> executable
        self._rank_compiled = {}  # (num_badges, words) -> executable
        self._select_compiled = {}  # (n, k) -> executable

    @staticmethod
    def stage(params_list, group_size: Optional[int] = None):
        """Stack member checkpoints and START the host->device upload.

        ``jax.device_put`` is asynchronous, so staging group i+1 BEFORE
        walking group i's badges overlaps the next group's weight transfer
        with the current group's badge scoring — the double buffer the
        grouped study walk in ``eval_prioritization`` drives. Pads a ragged
        tail to ``group_size`` by repeating member 0 (the inert-padding
        contract; the member-valid scalar keeps pad members unpickable).
        """
        import jax

        from simple_tip_tpu.parallel.ensemble import stack_params

        g = int(group_size or len(params_list))
        members = list(params_list) + [params_list[0]] * (g - len(params_list))
        return jax.device_put(stack_params(members))

    # -- program resolution ---------------------------------------------------

    def _n_neurons(self, x_shape, x_dtype) -> int:
        """Flattened tapped-activation width for one badge shape (shape-only
        ``jax.eval_shape`` — no compile, no dispatch)."""
        import jax

        member_specs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype),
            self.params_list[0],
        )
        x_spec = jax.ShapeDtypeStruct(tuple(x_shape), x_dtype)
        _, taps = jax.eval_shape(
            lambda p, xb: self.model_def.apply({"params": p}, xb, train=False),
            member_specs,
            x_spec,
        )
        acts = [taps[i] for i in self.layer_ids]
        return sum(int(np.prod(a.shape[1:])) for a in acts)

    def _tables_for(self, n_neurons: int):
        """The member cut tables stacked over the G axis, device-resident
        (pad members repeat member 0's table, matching the padded stack)."""
        import jax

        cached = self._tables.get(n_neurons)
        if cached is not None:
            return cached
        per_member = [cb.table(n_neurons) for cb in self._codebooks]
        per_member += [per_member[0]] * (self.group_size - self.n_members)
        stacked = tuple(
            np.stack([t[i] for t in per_member]) for i in range(3)
        )
        entry = jax.device_put(stacked)
        self._tables[n_neurons] = entry
        return entry

    def _chain_program(self, x_shape, x_dtype):
        import jax

        key = (tuple(x_shape), str(x_dtype))
        prog = self._chain_compiled.get(key)
        if prog is None:
            n_neurons = self._n_neurons(x_shape, x_dtype)
            k_cuts = len(self._codebooks[0]._cuts)
            # thresholds are runtime INPUTS here, so only the coding
            # STRUCTURE keys the program; the config-only metrics the
            # codebook does not cover (TKNC) stay baked and key as usual
            baked = {
                mid: m
                for mid, m in self.metrics.items()
                if not self._codebooks[0].covers(mid)
            }
            fp = program_fingerprint(
                self.model_def,
                self.stacked_params,
                self.layer_ids,
                baked,
                x_shape,
                x_dtype,
                "group_chain",
                f"group={self.group_size}",
                f"spec={self._spec_sig}",
                f"table={n_neurons}x{k_cuts}",
            )
            param_specs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype),
                self.stacked_params,
            )
            table_specs = tuple(
                jax.ShapeDtypeStruct(
                    (self.group_size, n_neurons, k_cuts), np.dtype(dt)
                )
                for dt in (np.float32, bool, np.int32)
            )
            x_spec = jax.ShapeDtypeStruct(tuple(x_shape), x_dtype)
            scalar_i32 = jax.ShapeDtypeStruct((), np.dtype(np.int32))
            prog = aot_compile(
                self._group_jit,
                (param_specs, table_specs, x_spec, scalar_i32, scalar_i32),
                self.cache,
                fp,
                program="group_chain",
            )
            self._chain_compiled[key] = prog
        return prog

    def _rank_program(self, num_badges: int, words: int):
        import jax

        key = (num_badges, words)
        prog = self._rank_compiled.get(key)
        if prog is None:
            fp = rank_fingerprint(
                num_badges,
                self.badge_size,
                words,
                f"group={self.group_size}",
            )
            spec = tuple(
                jax.ShapeDtypeStruct(
                    (self.group_size, self.badge_size, words),
                    np.dtype(np.uint32),
                )
                for _ in range(num_badges)
            )
            prog = aot_compile(
                self._grank_jit, (spec,), self.cache, fp, program="group_rank"
            )
            self._rank_compiled[key] = prog
        return prog

    def _select_program(self, n: int, k: int):
        import jax

        from simple_tip_tpu.ops.fused_chain import make_group_select_fn

        key = (int(n), int(k))
        prog = self._select_compiled.get(key)
        if prog is None:
            fp = select_fingerprint(n, k, f"group={self.group_size}")
            spec = (
                jax.ShapeDtypeStruct(
                    (self.group_size, int(n)), np.dtype(np.float32)
                ),
                jax.ShapeDtypeStruct((), np.dtype(np.int32)),
            )
            prog = aot_compile(
                jax.jit(make_group_select_fn(int(k))),
                spec,
                self.cache,
                fp,
                program="group_select",
            )
            self._select_compiled[key] = prog
        return prog

    # -- evaluation -----------------------------------------------------------

    def evaluate_dataset(self, x: np.ndarray, rngs=None, select_k=None):
        """Grouped prio evaluation of one test set: one chain dispatch per
        badge scores ALL members; one rank dispatch per metric ranks all
        members' CAM walks.

        Returns a LIST of per-member result dicts (real members only, in
        constructor order), each with the exact
        ``FusedChainRunner.evaluate_dataset`` contract. ``rngs`` is an
        optional per-member rng list for the MC-dropout VR pass (the
        stochastic vote pass stays per-member — it cannot fuse into the
        deterministic group program without changing the per-model vote
        streams the parity pin protects). Group wall-clock times are
        attributed to members as the 1/G amortized share.
        """
        from simple_tip_tpu.ops.prioritizers import _with_score_tail

        n = int(x.shape[0])
        bs = self.badge_size
        m = self.n_members
        x = np.asarray(x)
        prog = self._chain_program((bs,) + x.shape[1:], x.dtype)
        tables = self._tables_for(self._n_neurons((bs,) + x.shape[1:], x.dtype))

        preds = [[] for _ in range(m)]
        unc_acc = [{} for _ in range(m)]
        score_acc = [{} for _ in range(m)]
        packed_acc: Dict[str, list] = {mid: [] for mid in self.metrics}
        chain_s = 0.0
        for start in range(0, n, bs):
            xb = x[start : start + bs]
            valid = xb.shape[0]
            if valid < bs:
                xb = np.concatenate(
                    [xb, np.zeros((bs - valid,) + x.shape[1:], x.dtype)]
                )
            timer = Timer()
            with timer:
                pred_b, unc_b, cov_b = prog(
                    self.stacked_params,
                    tables,
                    xb,
                    np.int32(valid),
                    np.int32(m),
                )
                obs.counter("run_program.group_chain_dispatches").inc()
                pb = np.asarray(pred_b)
                for g in range(m):
                    preds[g].append(pb[g, :valid])
                for name, u in unc_b.items():
                    ub = np.asarray(u)
                    for g in range(m):
                        unc_acc[g].setdefault(name, []).append(ub[g, :valid])
                for mid, (s, p) in cov_b.items():
                    sb = np.asarray(s)
                    for g in range(m):
                        score_acc[g].setdefault(mid, []).append(sb[g, :valid])
                    packed_acc[mid].append(p)  # [G, bs, W], stays on device
            chain_s += timer.get()
            _observe_dispatch("group_chain", timer.get())

        share = chain_s / m  # amortized per-member chain time
        results = []
        for g in range(m):
            pred = np.concatenate(preds[g], axis=0)
            uncertainties = {
                k: np.concatenate(v, axis=0) for k, v in unc_acc[g].items()
            }
            scores = {
                k: np.concatenate(v, axis=0) for k, v in score_acc[g].items()
            }
            unc_times = {name: [0, share, 0.0, 0] for name in uncertainties}
            cov_times = {
                mid: [self.workers[g].setup_times[mid], share, 0.0]
                for mid in self.metrics
            }
            results.append(
                {
                    "pred": pred,
                    "uncertainties": uncertainties,
                    "unc_times": unc_times,
                    "scores": scores,
                    "cam_orders": {},
                    "cov_times": cov_times,
                }
            )

        for mid in self.metrics:
            badges = packed_acc[mid]
            words = int(badges[0].shape[2])
            rank_prog = self._rank_program(len(badges), words)
            timer = Timer(name="run_program.group_rank", metric=mid)
            with timer:
                picked_dev, count_dev = rank_prog(tuple(badges))
                obs.counter("run_program.group_rank_dispatches").inc()
                picked_all = np.asarray(picked_dev)
                counts = np.asarray(count_dev)
            rank_share = timer.get() / m
            _observe_dispatch("group_rank", timer.get())
            for g in range(m):
                picked = picked_all[g, : int(counts[g])].astype(np.int64)
                order = _with_score_tail(results[g]["scores"][mid], picked)
                results[g]["cov_times"][mid].append(rank_share)
                results[g]["cam_orders"][mid] = order
                FusedChainRunner._sanity_check(order, results[g]["scores"][mid])

        if rngs is not None and getattr(self.model_def, "has_dropout", False):
            for g in range(m):
                self._add_variation_ratio(
                    g,
                    x,
                    rngs[g],
                    results[g]["uncertainties"],
                    results[g]["unc_times"],
                )
        if select_k:
            padded_n = -(-n // bs) * bs
            sel_prog = self._select_program(padded_n, int(select_k))
            for name in results[0]["uncertainties"]:
                vals = np.zeros((self.group_size, padded_n), np.float32)
                for g in range(m):
                    vals[g, :n] = np.asarray(
                        results[g]["uncertainties"][name], np.float32
                    )
                timer = Timer()
                with timer:
                    picked = np.asarray(sel_prog(vals, np.int32(n)))
                obs.counter("run_program.select_dispatches").inc()
                _observe_dispatch("group_select", timer.get())
                for g in range(m):
                    results[g].setdefault("al_select", {})[name] = picked[
                        g
                    ].astype(np.int64)
        return results

    def _add_variation_ratio(self, g, x, rng, uncertainties, unc_times):
        """Member ``g``'s MC-dropout VR, exactly as the per-model path
        computes it (same vote function, same rng stream, same batch
        policy) — parity requires the per-member vote streams unchanged."""
        from simple_tip_tpu.engine.model_handler import DROPOUT_SAMPLE_SIZE
        from simple_tip_tpu.models.train import mc_dropout_votes

        sampling_timer = Timer()
        with sampling_timer:
            counts = mc_dropout_votes(
                self.model_def,
                self.params_list[g],
                x,
                n_samples=DROPOUT_SAMPLE_SIZE,
                rng=rng,
                batch_size=max(self.batch_size, 128),
            )
        quant_timer = Timer()
        with quant_timer:
            majority_count = counts.max(axis=1)
            vr = 1.0 - majority_count / DROPOUT_SAMPLE_SIZE
        uncertainties["VR"] = vr
        unc_times["VR"] = [0, sampling_timer.get(), quant_timer.get(), 0]

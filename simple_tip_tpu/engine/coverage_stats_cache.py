"""Cross-process disk cache of the coverage train-stats aggregates.

``CoverageWorker`` opens with one full pass over the training set to collect
the per-neuron mins / maxs / Welford stds that parameterize NBC, SNAC and
KMNC. HOST_PHASE.json prices that pass at ~28 s/run on the paper workload —
and ``run_scheduler`` spawns a fresh interpreter per phase, so before this
cache every scheduler process paid it again for the SAME (params, train set,
tap layers) triple. The aggregates are tiny (three 1-D float arrays per
neuron axis), pure functions of that triple, and expensive to recompute:
the textbook disk-cache shape.

Semantics mirror ``SAFitCache`` (engine/sa_prep.py): one pickle keyed by a
content fingerprint, atomic writes so concurrent scheduler workers can share
one dir, meta verified on load, and ANY read/unpickle failure degrading to a
recompute — a corrupt cache can cost time, never correctness. Unlike the SA
fingerprint, the key carries NO cluster-backend tag: the aggregates do not
depend on how downstream estimators are fitted.
"""

import logging
import os
import pickle
from typing import Optional, Sequence, Tuple

import numpy as np

from simple_tip_tpu import obs
from simple_tip_tpu.utils.artifacts_io import atomic_write_bytes

logger = logging.getLogger(__name__)

#: Bump when the entry layout or the aggregate-statistics definition changes;
#: stale-version entries are treated as misses.
COV_STATS_FORMAT_VERSION = "cov-stats-cache-v1"


def _as_host(stat):
    """Aggregates are per-layer lists of arrays (ragged across tap widths);
    materialize each leaf as host numpy without coercing the list shape."""
    if isinstance(stat, (list, tuple)):
        return [np.asarray(a) for a in stat]
    return np.asarray(stat)


class CoverageStatsCache:
    """Disk cache of one ``(mins, maxs, std)`` aggregate-statistics triple."""

    def __init__(self, root: str, fingerprint: str):
        self.root = root
        self.fingerprint = fingerprint
        # Same open-path hygiene as SAFitCache: sweep aged orphan tmp
        # files a mid-rename kill left behind in this cache dir.
        from simple_tip_tpu.utils.artifacts_io import sweep_orphan_tmp

        sweep_orphan_tmp(self.root)

    @classmethod
    def from_env(
        cls, params, training_set, activation_layers: Sequence
    ) -> Optional["CoverageStatsCache"]:
        """Cache handle per ``TIP_COV_STATS_CACHE_DIR`` policy, or None when
        off (``off``/``0``; default ``$TIP_ASSETS/coverage_stats_cache``)."""
        raw = os.environ.get("TIP_COV_STATS_CACHE_DIR", "").strip()
        if raw.lower() in ("off", "0"):
            return None
        if not raw:
            from simple_tip_tpu.config import output_folder

            raw = os.path.join(output_folder(), "coverage_stats_cache")
        from simple_tip_tpu.engine.sa_prep import content_fingerprint

        fp = content_fingerprint(
            COV_STATS_FORMAT_VERSION, params, training_set, activation_layers
        )
        return cls(root=raw, fingerprint=fp)

    @property
    def path(self) -> str:
        return os.path.join(self.root, f"cov_stats_{self.fingerprint[:16]}.pkl")

    def load(self) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """The cached ``(mins, maxs, std)``, or None on miss/stale/corrupt."""
        path = self.path
        try:
            with open(path, "rb") as f:
                entry = pickle.load(f)
            meta = entry["meta"]
            if (
                meta["version"] != COV_STATS_FORMAT_VERSION
                or meta["fingerprint"] != self.fingerprint
            ):
                logger.info("coverage-stats cache STALE (%s)", path)
                obs.counter("cov_stats_cache.stale").inc()
                obs.event("cov_stats_cache", outcome="stale")
                return None
            mins, maxs, std = entry["stats"]
            obs.counter("cov_stats_cache.hit").inc()
            obs.event("cov_stats_cache", outcome="hit")
            logger.info("coverage-stats cache HIT (%s)", path)
            return _as_host(mins), _as_host(maxs), _as_host(std)
        except FileNotFoundError:
            obs.counter("cov_stats_cache.miss").inc()
            obs.event("cov_stats_cache", outcome="miss")
            return None
        except Exception as e:  # noqa: BLE001 — any corrupt entry degrades to recompute
            logger.warning(
                "coverage-stats cache entry corrupt (%s: %r); recomputing", path, e
            )
            obs.counter("cov_stats_cache.corrupt").inc()
            obs.event("cov_stats_cache", outcome="corrupt")
            return None

    def store(self, stats: Tuple[np.ndarray, np.ndarray, np.ndarray]) -> None:
        """Persist the aggregates (atomic; failures warn, never raise)."""
        mins, maxs, std = stats
        try:
            os.makedirs(self.root, exist_ok=True)
            entry = {
                "meta": {
                    "version": COV_STATS_FORMAT_VERSION,
                    "fingerprint": self.fingerprint,
                },
                "stats": (_as_host(mins), _as_host(maxs), _as_host(std)),
            }
            atomic_write_bytes(self.path, pickle.dumps(entry, protocol=4))
            logger.info("coverage-stats cache stored (%s)", self.path)
            obs.counter("cov_stats_cache.store").inc()
        except Exception as e:  # noqa: BLE001 — cache is an optimization only
            logger.warning("coverage-stats cache store failed (%r)", e)

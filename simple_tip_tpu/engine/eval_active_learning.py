"""Active-learning experiment phase for one model run.

Behavioral contract matches the reference (reference:
src/dnn_test_prio/eval_active_learning.py): split nominal and ood test sets
into observed/future halves seeded by the model id, evaluate the original
model on all four splits, build ~40 per-TIP selections of ``num_selected``
observed samples (uncertainty top-k; NC scores top-k and CAM-first-k; SA top-k
and CAM-first-k; random baseline), retrain from scratch on train+selection for
EACH selection, evaluate the retrained model on all four splits, and pickle
``active_learning/{cs}_{model}_{metric}_{oodnom}.pickle``.

This phase is the reference's wall-clock monster (~80 full retrainings per
run); the parallel layer (simple_tip_tpu.parallel) runs the retrainings as a
vmapped parameter ensemble across devices instead of serializing them.
Determinism fix-with-flag: the reference's retrain shuffle is unseeded
(eval_active_learning.py:172); we seed it from (model_id, metric) unless
``deterministic=False``.
"""

import logging
import os
import pickle
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
from sklearn.model_selection import train_test_split

from simple_tip_tpu.config import subdir
from simple_tip_tpu.engine.coverage_handler import CoverageWorker
from simple_tip_tpu.engine.model_handler import BaseModel
from simple_tip_tpu.engine.surprise_handler import SurpriseHandler

logger = logging.getLogger(__name__)

RANDOM_SPLIT = "random"

SplitDataset = Dict[Tuple[str, str], Tuple[np.ndarray, np.ndarray]]
SplitEvaluation = Dict[Tuple[str, str], float]
MetricSelection = Dict[Tuple[str, str], List[int]]

NOM = "nominal"
OOD = "ood"
OBS = "observed"
FUT = "future"

TrainingProcess = Callable[[np.ndarray, np.ndarray, int], Tuple[object, object]]
"""(x, y_onehot, seed) -> (model_def, params): retrains a model from scratch."""

BatchTrainingProcess = Callable[
    [List[Tuple[np.ndarray, np.ndarray, int]]], List[Tuple[object, object]]
]
"""[(x_sel, y_sel, seed)] -> [(model_def, params)]: retrains one model per
selection, typically as a vmapped ensemble (parallel/al_ensemble.py)."""

Evaluator = Callable[[object, object, np.ndarray, np.ndarray], float]


def evaluate(
    model_id: int,
    case_study: str,
    model_def,
    params,
    train_x: np.ndarray,
    train_y: np.ndarray,
    nominal_test_x: np.ndarray,
    nominal_test_labels: np.ndarray,
    ood_test_x: np.ndarray,
    ood_test_labels: np.ndarray,
    nc_activation_layers: List,
    sa_activation_layers: List[int],
    training_process: TrainingProcess,
    observed_share: float,
    num_selected: int,
    num_classes: Optional[int],
    accuracy_fn: Evaluator,
    dsa_badge_size: Optional[int] = None,
    batch_size: int = 128,
    batch_training_process: Optional[BatchTrainingProcess] = None,
) -> None:
    """Evaluate the active-learning capabilities of every TIP for one run."""
    active_datasets = _shuffle_and_split_datasets(
        model_id,
        nominal_test_x,
        nominal_test_labels,
        ood_test_x,
        ood_test_labels,
        observed_share=observed_share,
    )

    smallest_observed = min(
        len(x) for (_, split), (x, _) in active_datasets.items() if split == OBS
    )
    if num_selected > smallest_observed:
        # Smoke-test-sized datasets can't supply the configured selection
        # size; clamp with a loud warning instead of tripping the sanity
        # check downstream. Real case-study data is never in this regime.
        logger.warning(
            "num_selected=%d exceeds the smallest observed split (%d) — clamping",
            num_selected,
            smallest_observed,
        )
        num_selected = smallest_observed

    original_model_eval = _evaluate(model_def, params, active_datasets, accuracy_fn)

    selections: MetricSelection = {}
    selections.update(
        _get_fp_selection(model_def, params, active_datasets, num_selected, batch_size)
    )
    selections.update(
        _get_nc_selection(
            model_def,
            params,
            train_x,
            active_datasets,
            nc_activation_layers,
            num_selected,
            batch_size,
        )
    )
    selections.update(
        _get_sa_selection(
            model_def,
            params,
            train_x,
            active_datasets,
            sa_activation_layers,
            num_selected,
            dsa_badge_size,
            case_study=case_study,
            model_id=model_id,
        )
    )
    selections.update(_get_random_section(active_datasets, num_selected))

    _selection_sanity_checks(num_selected, selections)

    active_accuracies = {}
    if batch_training_process is not None:
        # Ensemble path: all retrainings train simultaneously on device.
        sels = []
        for i, ((metric, ood_or_nom), selected_indexes) in enumerate(selections.items()):
            x = active_datasets[ood_or_nom, OBS][0][selected_indexes]
            y = active_datasets[ood_or_nom, OBS][1][selected_indexes]
            sels.append((x, np.asarray(y).flatten(), model_id * 1000 + i))
        retrained = batch_training_process(sels)
        for ((metric, ood_or_nom), _), (new_model_def, new_params) in zip(
            selections.items(), retrained
        ):
            active_accuracies[(metric, ood_or_nom)] = _evaluate(
                new_model_def, new_params, active_datasets, accuracy_fn
            )
    else:
        for i, ((metric, ood_or_nom), selected_indexes) in enumerate(selections.items()):
            x = active_datasets[ood_or_nom, OBS][0][selected_indexes]
            y = active_datasets[ood_or_nom, OBS][1][selected_indexes]
            new_model_def, new_params = _retrain(
                num_classes, training_process, train_x, train_y, x, y,
                seed=model_id * 1000 + i,
            )
            # Evaluate on all four splits (cheap now, interesting later).
            active_accuracies[(metric, ood_or_nom)] = _evaluate(
                new_model_def, new_params, active_datasets, accuracy_fn
            )

    _save_results_on_file(case_study, model_id, "original", "na", original_model_eval)
    for (metric, ood_or_nom), eval_res in active_accuracies.items():
        _save_results_on_file(case_study, model_id, metric, ood_or_nom, eval_res)


def _save_results_on_file(
    case_study: str, model_id: int, metric: str, ood_or_nom: str, eval_res: SplitEvaluation
) -> None:
    path = os.path.join(
        subdir("active_learning"),
        f"{case_study}_{model_id}_{metric}_{ood_or_nom}.pickle",
    )
    with open(path, "wb") as f:
        pickle.dump(eval_res, f)


def _selection_sanity_checks(num_selected, selections):
    for (metric, ood_or_nom), selected_idx in selections.items():
        assert len(selected_idx) == num_selected, (
            f"The number of selected indexes for {metric}, {ood_or_nom} is not "
            f"correct. Should be {num_selected}, but was {len(selected_idx)}"
        )
        assert (
            len(set(np.asarray(selected_idx).tolist())) == num_selected
        ), f"The number of selected indexes for {metric}, {ood_or_nom} is not unique."


def _retrain(num_classes, training_process, train_x, train_y, new_x, new_y, seed: int):
    """Retrain from scratch on train + selected data (reshuffled, one-hot)."""
    x = np.concatenate((train_x, new_x))
    assert train_y.shape[0] == np.prod(train_y.shape)
    assert new_y.shape[0] == np.prod(new_y.shape)
    y = np.concatenate((np.asarray(train_y).flatten(), np.asarray(new_y).flatten()))
    shuffled_idx = np.random.RandomState(seed).permutation(len(x))
    x = x[shuffled_idx]
    y = y[shuffled_idx]
    if num_classes is not None:
        y = np.eye(num_classes, dtype=np.float32)[y.astype(np.int64)]
    return training_process(x, y, seed)


def _get_random_section(dataset: SplitDataset, num_selected: int) -> MetricSelection:
    """Random selection baseline (the arrays are already shuffled)."""
    res: MetricSelection = {}
    for (ood_or_nom, observed_or_future), (x, y) in dataset.items():
        if observed_or_future == OBS:
            res[RANDOM_SPLIT, ood_or_nom] = [i for i in range(num_selected)]
    return res


def _get_fp_selection(
    model_def, params, datasets: SplitDataset, num_selected: int, batch_size: int
) -> MetricSelection:
    """Selection by fault-predictor (uncertainty) top-k."""
    res: MetricSelection = {}
    base_model = BaseModel(model_def, params, activation_layers=None, batch_size=batch_size)
    for (ood_or_nom, observed_or_future), (x, y) in datasets.items():
        if observed_or_future == OBS:
            _, uncertainties, _ = base_model.get_pred_and_uncertainty(x)
            for metric, uncertainty in uncertainties.items():
                res[metric, ood_or_nom] = np.argsort(uncertainty)[-num_selected:]
    return res


def _get_nc_selection(
    model_def,
    params,
    train_x: np.ndarray,
    datasets: SplitDataset,
    nc_activation_layers: List,
    num_selected: int,
    batch_size: int,
) -> MetricSelection:
    """Selection by neuron-coverage score top-k and CAM-first-k."""
    res: MetricSelection = {}
    nc_worker = CoverageWorker(
        base_model=BaseModel(
            model_def, params, activation_layers=nc_activation_layers, batch_size=batch_size
        ),
        training_set=train_x,
    )
    for (ood_or_nom, observed_or_future), (x, y) in datasets.items():
        if observed_or_future == OBS:
            # ds_id carries num_selected for temp-dir naming, mirroring the
            # reference's (harmless) argument quirk (eval_active_learning.py:230).
            _, all_scores, cam_orders = nc_worker.evaluate_all(x, num_selected)
            for metric, scores in all_scores.items():
                res[metric, ood_or_nom] = np.argsort(scores)[-num_selected:]
            for metric, cam_order in cam_orders.items():
                res[f"{metric}-cam", ood_or_nom] = cam_order[:num_selected]
    return res


def _get_sa_selection(
    model_def,
    params,
    train_x: np.ndarray,
    datasets: SplitDataset,
    sa_activation_layers: List[int],
    num_selected: int,
    dsa_badge_size: Optional[int] = None,
    case_study: Optional[str] = None,
    model_id: Optional[int] = None,
) -> MetricSelection:
    """Selection by surprise-adequacy top-k and SC-CAM-first-k.

    ``case_study``/``model_id`` key the SA fit cache: the prio phase fits
    the same (model, train set, sa_layers) triple, so this phase normally
    runs against a warm cache and skips every fit (engine/sa_prep.py)."""
    res: MetricSelection = {}
    sa_worker = SurpriseHandler(
        model_def,
        params,
        sa_layers=sa_activation_layers,
        training_dataset=train_x,
        case_study=case_study,
        model_id=model_id,
    )
    results = sa_worker.evaluate_all(
        datasets={NOM: datasets[NOM, OBS][0], OOD: datasets[OOD, OBS][0]},
        dsa_badge_size=dsa_badge_size,
    )
    for metric, values in results.items():
        for nom_or_ood, (sa, cam_order, _) in values.items():
            res[metric, nom_or_ood] = np.argsort(sa)[-num_selected:]
            res[f"{metric}-cam", nom_or_ood] = cam_order[:num_selected]
    return res


def _shuffle_and_split_datasets(
    model_id: int,
    nominal_x: np.ndarray,
    nominal_y: np.ndarray,
    ood_x: np.ndarray,
    ood_y: np.ndarray,
    observed_share: float,
) -> SplitDataset:
    """Shuffle and split both test sets into observed/future, seeded by run id."""
    res: SplitDataset = {}
    fut_x, obs_x, fut_y, obs_y = train_test_split(
        nominal_x, nominal_y, test_size=observed_share, random_state=model_id
    )
    res[NOM, OBS] = (obs_x, obs_y)
    res[NOM, FUT] = (fut_x, fut_y)
    fut_x, obs_x, fut_y, obs_y = train_test_split(
        ood_x, ood_y, test_size=observed_share, random_state=model_id
    )
    res[OOD, OBS] = (obs_x, obs_y)
    res[OOD, FUT] = (fut_x, fut_y)
    return res


def _evaluate(
    model_def, params, datasets: SplitDataset, accuracy_fn: Evaluator
) -> SplitEvaluation:
    """Accuracy of the model on all four dataset splits."""
    res: SplitEvaluation = {}
    for (ood_or_nom, observed_or_future), (x, y) in datasets.items():
        acc = accuracy_fn(model_def, params, x, y)
        assert 0 <= acc <= 1, (
            "The models metric is not accuracy, change your training_process callable."
        )
        res[ood_or_nom, observed_or_future] = acc
    return res

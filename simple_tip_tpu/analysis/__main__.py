"""``python -m simple_tip_tpu.analysis`` — run the tiplint CLI."""

import sys

from simple_tip_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())

"""tiplint: JAX/TPU-aware static analysis for the simple_tip_tpu codebase.

A self-contained (stdlib-``ast``, zero third-party imports) linter catching
the defect classes that sink TPU systems statically: impure jitted functions,
reused PRNG keys, implicit host↔device syncs in hot paths, f64 dtypes that
silently downcast on TPU, undonated multi-GB ensemble buffers, drift in the
filesystem artifact contract between the engine (writers) and the plotters
(readers), and docstring-coverage regressions. A whole-program layer
(``analysis.graph``: imports, call graph, jit/shard_map boundaries, mesh and
PartitionSpec index) backs the cross-module rules: sharding-spec-mismatch,
shape-polymorphism and transitive-jit-purity.

Usage::

    python -m simple_tip_tpu.analysis [paths...] [--format text|json|github]
    python -m simple_tip_tpu.analysis --list-rules

Suppress an intentional finding inline with a justification comment
(a suppression that stops matching anything is itself reported as
``unused-suppression``, so the example below names no real rule)::

    x = np.asarray(batch, dtype=np.float64)  # tiplint: disable=<rule>

See README.md section "Static analysis (tiplint)" for the rule catalogue.
"""

from simple_tip_tpu.analysis.core import (  # noqa: F401
    Finding,
    ModuleInfo,
    Rule,
    all_rules,
    analyze_paths,
    register,
    unsuppressed,
)
from simple_tip_tpu.analysis.cli import main  # noqa: F401

"""tiplint run cache: skip re-analysis when nothing it reads has changed.

The dataflow rules (PR 16) made a whole-package sweep meaningfully more
expensive than the old syntactic pass — interprocedural fixed points over
the project graph are not free. This cache makes the *second* identical
run (pre-commit after CI, a re-run in the same worktree, the determinism
gate in lint.yml) near-instant without any soundness risk: the key is a
sha256 over

- the stat signature (relpath, size, mtime_ns) of **every analyzed .py
  file** — edit any input and the key moves;
- the stat signature of **the analyzer's own source tree**
  (``simple_tip_tpu/analysis/**``) — edit a rule or the engine and every
  prior entry is dead, no version constant to forget to bump;
- the ``select`` restriction, since it changes which rules ran.

Entries are whole-run finding lists, stored as deterministic JSON and
published atomically (pid-unique tmp + ``os.replace``), so a cache hit
renders byte-identically to the run that populated it. The store is
pruned to the most recent :data:`MAX_ENTRIES` by mtime. Stdlib-only,
like everything under ``analysis/``.
"""

import hashlib
import json
import os
from typing import Iterable, List, Optional, Sequence, Tuple

from simple_tip_tpu.analysis.core import Finding, iter_python_files

#: Cache entries kept after pruning (oldest-mtime entries beyond this go).
MAX_ENTRIES = 32

_SCHEMA = 1


def _stat_sig(path: str, rel: str) -> Optional[Tuple[str, int, int]]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (rel, st.st_size, st.st_mtime_ns)


def _analyzer_files() -> Iterable[Tuple[str, str]]:
    root = os.path.dirname(os.path.abspath(__file__))
    for path, _ in iter_python_files([root]):
        yield path, os.path.relpath(path, root).replace(os.sep, "/")


def run_key(
    paths: Sequence[str], select: Optional[Sequence[str]]
) -> str:
    """The cache key for analyzing ``paths`` under ``select`` right now."""
    sigs: List[Tuple[str, Tuple]] = []
    for path, root in iter_python_files(paths):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        sig = _stat_sig(path, f"{os.path.basename(root)}/{rel}")
        if sig is not None:
            sigs.append(("in", sig))
    for path, rel in _analyzer_files():
        sig = _stat_sig(path, rel)
        if sig is not None:
            sigs.append(("self", sig))
    payload = json.dumps(
        {
            "schema": _SCHEMA,
            "select": sorted(select) if select else None,
            "files": sorted(sigs),
        },
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


def _entry_path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, f"tiplint_{key}.json")


def load(cache_dir: str, key: str) -> Optional[List[Finding]]:
    """The cached finding list for ``key``, or None (miss/corrupt)."""
    try:
        with open(_entry_path(cache_dir, key), encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("schema") != _SCHEMA:
            return None
        return [
            Finding(
                rule=r["rule"],
                path=r["path"],
                line=int(r["line"]),
                message=r["message"],
                suppressed=bool(r["suppressed"]),
            )
            for r in doc["findings"]
        ]
    except (OSError, ValueError, KeyError, TypeError):
        return None


def store(cache_dir: str, key: str, findings: Sequence[Finding]) -> None:
    """Publish ``findings`` under ``key`` atomically; best-effort only."""
    doc = {
        "schema": _SCHEMA,
        "findings": [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "message": f.message,
                "suppressed": f.suppressed,
            }
            for f in findings
        ],
    }
    try:
        os.makedirs(cache_dir, exist_ok=True)
        final = _entry_path(cache_dir, key)
        tmp = f"{final}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, sort_keys=True)
        os.replace(tmp, final)
        _prune(cache_dir)
    except OSError:
        pass  # a cache that can't write is a slow run, not a failure


def _prune(cache_dir: str) -> None:
    entries = []
    for name in os.listdir(cache_dir):
        if name.startswith("tiplint_") and name.endswith(".json"):
            full = os.path.join(cache_dir, name)
            try:
                entries.append((os.stat(full).st_mtime_ns, full))
            except OSError:
                continue
    entries.sort(reverse=True)
    for _, full in entries[MAX_ENTRIES:]:
        try:
            os.remove(full)
        except OSError:
            continue

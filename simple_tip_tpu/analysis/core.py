"""tiplint core: module model, rule registry, suppressions, analyzer driver.

Pure stdlib (``ast`` + ``os`` + ``re``): the analyzer must run in
dependency-light environments (CI lint gate, pre-commit) where jax is not
installed, so nothing in ``simple_tip_tpu.analysis`` may import jax, numpy or
any third-party package.

Vocabulary:

- A **Rule** inspects parsed modules and yields findings. Per-module rules
  implement ``check_module``; whole-package rules (cross-file contracts)
  implement ``check_package``.
- A **Finding** is (rule, path, line, message). A finding is *suppressed*
  when the offending line (or a comment-only line directly above it) carries
  ``# tiplint: disable=<rule>[,<rule>...]``, or the file carries a
  file-level ``# tiplint: disable-file=<rule>`` anywhere. Suppressions are
  reported (so silent rot is visible) but do not fail the run.
"""

import ast
import os
import re
from dataclasses import dataclass, field, replace
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

_DISABLE_RE = re.compile(r"#\s*tiplint:\s*disable=([\w\-, ]+)")
_DISABLE_FILE_RE = re.compile(r"#\s*tiplint:\s*disable-file=([\w\-, ]+)")
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


@dataclass(frozen=True)
class Finding:
    """One static-analysis finding, anchored to a file and line."""

    rule: str
    path: str  # path relative to the analysis root (or absolute for stray files)
    line: int
    message: str
    suppressed: bool = False

    def format(self) -> str:
        """Render as the canonical ``path:line: [rule] message`` text line."""
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


@dataclass
class ModuleInfo:
    """One parsed source module plus its suppression table."""

    path: str  # absolute path on disk
    relpath: str  # path relative to the analysis root, always '/'-separated
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    # line number -> set of rule names disabled on that line ('all' wildcard)
    line_disables: Dict[int, Set[str]] = field(default_factory=dict)
    file_disables: Set[str] = field(default_factory=set)

    @classmethod
    def parse(cls, path: str, root: str) -> "ModuleInfo":
        """Read and parse ``path``; raises SyntaxError on unparsable source."""
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        info = cls(path=path, relpath=rel, source=source, tree=tree)
        info.lines = source.splitlines()
        for lineno, text in enumerate(info.lines, start=1):
            m = _DISABLE_FILE_RE.search(text)
            if m:
                info.file_disables.update(_split_rules(m.group(1)))
                continue
            m = _DISABLE_RE.search(text)
            if m:
                info.line_disables[lineno] = _split_rules(m.group(1))
        return info

    def is_suppressed(self, rule: str, line: int) -> bool:
        """True if ``rule`` is disabled at ``line`` (inline, previous
        comment-only line, or file-wide)."""
        if {"all", rule} & self.file_disables:
            return True
        here = self.line_disables.get(line, set())
        if {"all", rule} & here:
            return True
        # A standalone suppression comment may sit on its own line directly
        # above the flagged statement (useful for long expressions).
        prev = line - 1
        if 1 <= prev <= len(self.lines) and _COMMENT_ONLY_RE.match(self.lines[prev - 1]):
            if {"all", rule} & self.line_disables.get(prev, set()):
                return True
        return False


def _split_rules(spec: str) -> Set[str]:
    return {part.strip() for part in spec.split(",") if part.strip()}


class Rule:
    """Base class for tiplint rules.

    Subclasses set ``name``/``description`` and override ``check_module``
    (called once per file) and/or ``check_package`` (called once per run
    with every parsed module — for cross-file contracts). Both yield
    ``(relpath, line, message)`` triples; the driver owns Finding assembly
    and suppression bookkeeping.
    """

    name: str = ""
    description: str = ""

    def check_module(
        self, module: ModuleInfo
    ) -> Iterator[Tuple[str, int, str]]:
        """Per-file check; default: no findings."""
        return iter(())

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        """Whole-package check; default: no findings."""
        return iter(())


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate the rule and add it to the registry."""
    rule = rule_cls()
    assert rule.name, f"{rule_cls.__name__} must set a rule name"
    assert rule.name not in _REGISTRY, f"duplicate rule name {rule.name!r}"
    _REGISTRY[rule.name] = rule
    return rule_cls


def all_rules() -> Dict[str, Rule]:
    """name -> rule instance for every registered rule (registration happens
    on import of ``simple_tip_tpu.analysis.rules``)."""
    from simple_tip_tpu.analysis import rules as _rules  # noqa: F401 (side effect)

    return dict(_REGISTRY)


def iter_python_files(paths: Iterable[str]) -> Iterator[Tuple[str, str]]:
    """Yield (absolute file path, analysis root) for every .py under ``paths``.

    A directory argument is its own root (relpaths are computed against it);
    a file argument uses its parent directory as root. Hidden directories and
    __pycache__ are skipped.
    """
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            yield p, os.path.dirname(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames if not d.startswith(".") and d != "__pycache__"
            ]
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    yield os.path.join(dirpath, fname), p


def analyze_paths(
    paths: Sequence[str], select: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Run the (selected) rules over every module under ``paths``.

    Returns all findings, suppressed ones included (marked); callers decide
    what fails the run (the CLI exits non-zero on any unsuppressed finding).
    """
    rules = all_rules()
    if select:
        unknown = sorted(set(select) - set(rules))
        if unknown:
            raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
        rules = {name: rules[name] for name in select}

    modules: List[ModuleInfo] = []
    findings: List[Finding] = []
    by_rel: Dict[str, ModuleInfo] = {}
    for path, root in iter_python_files(paths):
        try:
            info = ModuleInfo.parse(path, root)
        except SyntaxError as exc:
            findings.append(
                Finding(
                    rule="parse-error",
                    path=os.path.relpath(path, root).replace(os.sep, "/"),
                    line=exc.lineno or 1,
                    message=f"could not parse: {exc.msg}",
                )
            )
            continue
        modules.append(info)
        by_rel[info.relpath] = info

    for rule in rules.values():
        raw: List[Tuple[str, int, str]] = []
        for module in modules:
            raw.extend(
                (module.relpath, line, msg)
                for _rel, line, msg in rule.check_module(module)
            )
        raw.extend(rule.check_package(modules))
        for rel, line, msg in raw:
            module = by_rel.get(rel)
            suppressed = module.is_suppressed(rule.name, line) if module else False
            findings.append(
                Finding(rule=rule.name, path=rel, line=line, message=msg,
                        suppressed=suppressed)
            )

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


def unsuppressed(findings: Iterable[Finding]) -> List[Finding]:
    """The findings that fail a lint run."""
    return [f for f in findings if not f.suppressed]

"""tiplint core: module model, rule registry, suppressions, analyzer driver.

Pure stdlib (``ast`` + ``os`` + ``re``): the analyzer must run in
dependency-light environments (CI lint gate, pre-commit) where jax is not
installed, so nothing in ``simple_tip_tpu.analysis`` may import jax, numpy or
any third-party package.

Vocabulary:

- A **Rule** inspects parsed modules and yields findings. Per-module rules
  implement ``check_module``; whole-package rules (cross-file contracts)
  implement ``check_package``.
- A **Finding** is (rule, path, line, message). A finding is *suppressed*
  when the offending line (or a comment-only line directly above it) carries
  ``# tiplint: disable=<rule>[,<rule>...]``, or the file carries a
  file-level ``# tiplint: disable-file=<rule>`` anywhere. Suppressions are
  reported (so silent rot is visible) but do not fail the run.
- A suppression that matches NO finding during a full (unselected) run is
  itself reported as a synthetic ``unused-suppression`` finding, so stale
  justification comments surface instead of rotting.
"""

import ast
import os
import re
from dataclasses import dataclass, field, replace
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

_DISABLE_RE = re.compile(r"#\s*tiplint:\s*disable=([\w\-, ]+)")
_DISABLE_FILE_RE = re.compile(r"#\s*tiplint:\s*disable-file=([\w\-, ]+)")
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


@dataclass(frozen=True)
class Finding:
    """One static-analysis finding, anchored to a file and line."""

    rule: str
    path: str  # path relative to the analysis root (or absolute for stray files)
    line: int
    message: str
    suppressed: bool = False
    # True when suppressed by a baseline fingerprint rather than an in-source
    # comment — reporters distinguish the two (SARIF: external vs inSource).
    baselined: bool = False

    def format(self) -> str:
        """Render as the canonical ``path:line: [rule] message`` text line."""
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


#: A suppression entry key: ``(lineno, rule)`` for a line disable, or
#: ``("file", rule)`` for a file-wide disable. ``rule`` may be ``"all"``.
SuppressionKey = Tuple[object, str]


@dataclass
class ModuleInfo:
    """One parsed source module plus its suppression table."""

    path: str  # absolute path on disk
    relpath: str  # path relative to the analysis root, always '/'-separated
    source: str
    tree: ast.Module
    root: str = ""  # the analysis root this module was found under
    lines: List[str] = field(default_factory=list)
    # line number -> set of rule names disabled on that line ('all' wildcard)
    line_disables: Dict[int, Set[str]] = field(default_factory=dict)
    # rule name -> line number of the first file-wide disable declaring it
    file_disables: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, root: str) -> "ModuleInfo":
        """Read and parse ``path``; raises SyntaxError on unparsable source."""
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        info = cls(path=path, relpath=rel, source=source, tree=tree, root=root)
        info.lines = source.splitlines()
        for lineno, text in enumerate(info.lines, start=1):
            m = _DISABLE_FILE_RE.search(text)
            if m:
                for name in _split_rules(m.group(1)):
                    info.file_disables.setdefault(name, lineno)
                continue
            m = _DISABLE_RE.search(text)
            if m:
                info.line_disables[lineno] = _split_rules(m.group(1))
        return info

    def suppression_match(self, rule: str, line: int) -> Optional[SuppressionKey]:
        """The suppression entry that disables ``rule`` at ``line`` (inline,
        previous comment-only line, or file-wide), or None.

        The returned key identifies the *source comment* that matched, so the
        driver can track which suppressions actually fire (unused-suppression
        reporting). Specific rule names win over the ``all`` wildcard."""
        here = self.line_disables.get(line, set())
        for name in (rule, "all"):
            if name in here:
                return (line, name)
        # A standalone suppression comment may sit on its own line directly
        # above the flagged statement (useful for long expressions).
        prev = line - 1
        if 1 <= prev <= len(self.lines) and _COMMENT_ONLY_RE.match(self.lines[prev - 1]):
            prevset = self.line_disables.get(prev, set())
            for name in (rule, "all"):
                if name in prevset:
                    return (prev, name)
        for name in (rule, "all"):
            if name in self.file_disables:
                return ("file", name)
        return None

    def is_suppressed(self, rule: str, line: int) -> bool:
        """True if ``rule`` is disabled at ``line`` (inline, previous
        comment-only line, or file-wide)."""
        return self.suppression_match(rule, line) is not None


def _split_rules(spec: str) -> Set[str]:
    return {part.strip() for part in spec.split(",") if part.strip()}


class Rule:
    """Base class for tiplint rules.

    Subclasses set ``name``/``description`` and override ``check_module``
    (called once per file) and/or ``check_package`` (called once per run
    with every parsed module — for cross-file contracts). Both yield
    ``(path, line, message)`` triples; the driver owns Finding assembly and
    suppression bookkeeping. ``check_module`` findings are attributed to the
    module being checked (the yielded path is ignored); ``check_package``
    rules must yield ``module.path`` (the absolute path) so attribution
    stays unambiguous when two analysis roots contain the same relative
    path — bare relpaths are accepted for compatibility when unique.
    """

    name: str = ""
    description: str = ""
    #: short classification labels (``("sharding", "semantic")``) surfaced by
    #: ``--list-rules`` and the generated README catalogue
    tags: Tuple[str, ...] = ()
    #: one-paragraph "why this matters" text for the README catalogue; falls
    #: back to ``description`` when empty
    rationale: str = ""

    def check_module(
        self, module: ModuleInfo
    ) -> Iterator[Tuple[str, int, str]]:
        """Per-file check; default: no findings."""
        return iter(())

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        """Whole-package check; default: no findings."""
        return iter(())


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate the rule and add it to the registry."""
    rule = rule_cls()
    assert rule.name, f"{rule_cls.__name__} must set a rule name"
    assert rule.name not in _REGISTRY, f"duplicate rule name {rule.name!r}"
    _REGISTRY[rule.name] = rule
    return rule_cls


def all_rules() -> Dict[str, Rule]:
    """name -> rule instance for every registered rule (registration happens
    on import of ``simple_tip_tpu.analysis.rules``)."""
    from simple_tip_tpu.analysis import rules as _rules  # noqa: F401 (side effect)

    return dict(_REGISTRY)


def iter_python_files(paths: Iterable[str]) -> Iterator[Tuple[str, str]]:
    """Yield (absolute file path, analysis root) for every .py under ``paths``.

    A directory argument is its own root (relpaths are computed against it);
    a file argument uses its parent directory as root. Hidden directories and
    __pycache__ are skipped.
    """
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            yield p, os.path.dirname(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [
                d for d in dirnames if not d.startswith(".") and d != "__pycache__"
            ]
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    yield os.path.join(dirpath, fname), p


def analyze_paths(
    paths: Sequence[str],
    select: Optional[Sequence[str]] = None,
    only_paths: Optional[Set[str]] = None,
) -> List[Finding]:
    """Run the (selected) rules over every module under ``paths``.

    Returns all findings, suppressed ones included (marked); callers decide
    what fails the run (the CLI exits non-zero on any unsuppressed finding).

    ``only_paths`` (absolute file paths) restricts *reporting* to those
    modules — the whole tree is still parsed so package rules see the full
    program, but per-module rules skip out-of-scope files and package-rule
    findings attributed elsewhere are dropped. This is the engine behind
    ``--changed-only``.

    When no ``select`` restriction is given AND the sweep was whole-project
    (no ``only_paths``), suppression comments that disabled nothing during
    the run are themselves reported as synthetic ``unused-suppression``
    findings (like ``parse-error``, not a registered rule), so stale
    suppressions can't rot silently after the code they justified is
    refactored away. A scoped run must NOT audit: a suppression whose rule
    fires only from out-of-scope files would be falsely reported stale.
    """
    rules = all_rules()
    if select:
        unknown = sorted(set(select) - set(rules))
        if unknown:
            raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
        rules = {name: rules[name] for name in select}

    modules: List[ModuleInfo] = []
    findings: List[Finding] = []
    # Modules are keyed by ABSOLUTE path (collision-free); the relpath table
    # is a convenience lookup for package rules, with entries that two roots
    # both claim (e.g. `simple_tip_tpu/__init__.py` and `tests/__init__.py`
    # when both directories are analyzed) poisoned to None so suppression
    # lookup can never consult the wrong module.
    by_key: Dict[str, ModuleInfo] = {}
    by_rel: Dict[str, Optional[ModuleInfo]] = {}
    for path, root in iter_python_files(paths):
        try:
            info = ModuleInfo.parse(path, root)
        except SyntaxError as exc:
            if only_paths is not None and path not in only_paths:
                continue
            findings.append(
                Finding(
                    rule="parse-error",
                    path=os.path.relpath(path, root).replace(os.sep, "/"),
                    line=exc.lineno or 1,
                    message=f"could not parse: {exc.msg}",
                )
            )
            continue
        modules.append(info)
        by_key[info.path] = info
        if info.relpath in by_rel and by_rel[info.relpath] is not info:
            by_rel[info.relpath] = None
        else:
            by_rel[info.relpath] = info

    # id(module) -> suppression keys that matched at least one finding
    used: Dict[int, Set[SuppressionKey]] = {}

    def display_path(module: ModuleInfo) -> str:
        # Prefix colliding relpaths with their root's basename so two files
        # from different roots never render identically in a report.
        if by_rel.get(module.relpath) is module:
            return module.relpath
        return f"{os.path.basename(module.root)}/{module.relpath}"

    def emit(rule_name: str, module: Optional[ModuleInfo],
             path_hint: Optional[str], line: int, msg: str) -> None:
        suppressed = False
        if module is not None:
            match = module.suppression_match(rule_name, line)
            if match is not None:
                suppressed = True
                used.setdefault(id(module), set()).add(match)
            path = display_path(module)
        else:
            path = path_hint or "<unknown>"
        findings.append(
            Finding(rule=rule_name, path=path, line=line, message=msg,
                    suppressed=suppressed)
        )

    def in_scope(module: Optional[ModuleInfo], path_hint: Optional[str]) -> bool:
        if only_paths is None:
            return True
        if module is not None:
            return module.path in only_paths
        return path_hint in only_paths

    for rule in rules.values():
        for module in modules:
            if not in_scope(module, None):
                continue
            for _rel, line, msg in rule.check_module(module):
                emit(rule.name, module, None, line, msg)
        for key, line, msg in rule.check_package(modules):
            # Package rules yield the module's absolute path (module.path);
            # bare relpaths are accepted for compatibility when unambiguous.
            module = by_key.get(key)
            if module is None:
                module = by_rel.get(key)
            if not in_scope(module, key):
                continue
            emit(rule.name, module, key, line, msg)

    if select is None and only_paths is None:
        _report_unused_suppressions(modules, rules, used, emit)

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


def _report_unused_suppressions(
    modules: Sequence[ModuleInfo],
    rules: Dict[str, Rule],
    used: Dict[int, Set[SuppressionKey]],
    emit,
) -> None:
    """Emit ``unused-suppression`` findings for disable comments that matched
    nothing. Runs only on full (unselected) runs — with ``--select`` most
    suppressions legitimately never fire.

    Two passes per module: ordinary rule names first, then stale
    ``unused-suppression`` disables — so a disable comment whose only job is
    to suppress an unused-suppression finding on the next line is counted as
    used before it is judged.
    """
    known = set(rules) | {"all", "parse-error", "unused-suppression"}

    def message(name: str, scope: str) -> str:
        if name not in known:
            return (
                f"suppression of unknown rule '{name}' ({scope}) matches "
                "nothing; fix the rule name or delete the comment"
            )
        return (
            f"suppression of '{name}' ({scope}) no longer matches any "
            "finding; delete the stale comment"
        )

    for module in modules:
        mused = used.setdefault(id(module), set())
        entries: List[Tuple[int, str, SuppressionKey, str]] = []
        for lineno, names in sorted(module.line_disables.items()):
            for name in sorted(names):
                entries.append((lineno, name, (lineno, name), "inline"))
        for name, lineno in sorted(module.file_disables.items()):
            entries.append((lineno, name, ("file", name), "file-wide"))
        for deferred in (False, True):
            for lineno, name, key, scope in entries:
                if (name == "unused-suppression") is not deferred:
                    continue
                if key in mused:
                    continue
                emit("unused-suppression", module, None, lineno,
                     message(name, scope))


def unsuppressed(findings: Iterable[Finding]) -> List[Finding]:
    """The findings that fail a lint run."""
    return [f for f in findings if not f.suppressed]

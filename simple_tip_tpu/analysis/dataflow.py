"""tiplint dataflow: def-use/reaching-definitions over per-function CFGs.

The per-file rules are syntactic (one statement at a time) and the project
graph (``analysis/graph.py``) is topological (who calls whom, who traces
whom). Neither can answer the questions the repo's runtime contracts
actually pose — *is this buffer read again after the jit donated it*, *does
this path string derive from a shared-bus root before it reaches a raw
write*, *which literal env name ends up inside that helper's
``os.environ.get``*. Those are dataflow questions, and this module is the
engine the flow-sensitive rules (``use-after-donate``, ``escaping-tracer``,
``unsafe-bus-write``, ``knob-contract``) are built on:

- **CFG**: a statement-level control-flow graph per function body, with
  branch joins (``if``/``try``/``match``), loop back edges (``for``/
  ``while``), and ``break``/``continue``/``return`` handled — so "after"
  means *along some execution path*, including the second loop iteration;
- **def/use**: per CFG node, the local names read and written, with
  aug-assign counting as both, attribute/subscript stores counting as reads
  of their base, and nested functions contributing their free-variable
  reads (a closure capture is a use) but never their local writes;
- **poison propagation** (:meth:`FunctionFlow.reaching_uses`): seed a name
  at a statement, kill it at redefinitions, report every read some path can
  still reach — the use-after-donate core;
- **taint propagation** (:func:`taint_names`): name-level fixed point over
  a function body with rule-supplied seeds, provenance *chains* (def site →
  each assignment hop → the violating use, rendered into findings), and a
  pid-uniqueness bit so the atomic tmp-file idiom is recognized;
- **interprocedural stitching** (:class:`ProjectFlow`): summaries over the
  project graph's call edges — "this helper's return value is bus-derived"
  and "this helper reads the env var its parameter names" — iterated to a
  fixed point, so ``_env("TIP_SERVE_INFLIGHT", ...)`` is a knob read at the
  call site and ``default_index_dir()`` taints every path built from it.

Everything is stdlib-``ast`` and best-effort: unresolved means unknown,
never unsafe. Like the graph, a :class:`ProjectFlow` is built once per run
(:func:`project_flow`, identity-cached on the module list).
"""

import ast
import re
from dataclasses import dataclass, field
from typing import (
    Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple,
)

from simple_tip_tpu.analysis.core import ModuleInfo
from simple_tip_tpu.analysis.graph import FunctionInfo, ProjectGraph, project_graph
from simple_tip_tpu.analysis.rules.common import (
    FunctionNode,
    callee_name,
    import_aliases,
    lambda_or_def_params,
    parent_map,
)

_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def scope_walk(node: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` minus nested function subtrees — the traversal for
    facts about *one* scope (inner defs keep their own environments)."""
    yield node
    for child in ast.iter_child_nodes(node):
        if not isinstance(child, _FUNCTION_NODES):
            yield from scope_walk(child)


def nested_defs(fn: FunctionNode) -> Dict[str, ast.AST]:
    """name -> def node for functions defined directly in ``fn``'s scope
    (closure helpers like ``def _num(var, default)`` inside ``from_env``
    — these are not project-graph functions, so call resolution to them
    is by local name)."""
    out: Dict[str, ast.AST] = {}
    body = fn.body if isinstance(fn.body, list) else [fn.body]

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[child.name] = child
            elif not isinstance(child, ast.Lambda):
                visit(child)

    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[stmt.name] = stmt
        else:
            visit(stmt)
    return out


# ---------------------------------------------------------------------------
# per-statement def/use extraction
# ---------------------------------------------------------------------------


def _free_reads(fn: FunctionNode) -> Set[str]:
    """Free-variable reads of a nested function (loads minus its own
    params and local writes) — a closure capture is a use at the def site."""
    reads: Set[str] = set()
    writes: Set[str] = set(lambda_or_def_params(fn))
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    reads.add(node.id)
                else:
                    writes.add(node.id)
    return reads - writes


def _collect(node: ast.AST, reads: Set[str], writes: Set[str]) -> None:
    """Accumulate name reads/writes of one expression/statement subtree.

    Nested function bodies contribute free reads only — their local
    writes must never kill a poison in the enclosing frame."""
    if isinstance(node, _FUNCTION_NODES):
        reads |= _free_reads(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            writes.add(node.name)
            for d in node.decorator_list:
                _collect(d, reads, writes)
            for default in node.args.defaults + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                _collect(default, reads, writes)
        return
    if isinstance(node, ast.ClassDef):
        writes.add(node.name)
        for d in node.decorator_list + node.bases:
            _collect(d, reads, writes)
        for stmt in node.body:  # class bodies execute: reads are real
            sub_w: Set[str] = set()
            _collect(stmt, reads, sub_w)  # class-namespace writes dropped
        return
    if isinstance(node, ast.Name):
        if isinstance(node.ctx, ast.Load):
            reads.add(node.id)
        else:
            writes.add(node.id)
        return
    if isinstance(node, ast.AugAssign):
        # x += ... reads AND writes x
        for sub in ast.walk(node.target):
            if isinstance(sub, ast.Name):
                reads.add(sub.id)
                writes.add(sub.id)
            elif isinstance(sub, (ast.Attribute, ast.Subscript)):
                _collect(sub.value, reads, writes)
        _collect(node.value, reads, writes)
        return
    if isinstance(node, (ast.Import, ast.ImportFrom)):
        for a in node.names:
            writes.add((a.asname or a.name).split(".")[0])
        return
    for child in ast.iter_child_nodes(node):
        _collect(child, reads, writes)


def _own_parts(stmt: ast.stmt) -> List[ast.AST]:
    """The AST fragments a compound statement's *own* CFG node evaluates
    (its header), or the whole statement for simple statements."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter, stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        parts: List[ast.AST] = []
        for item in stmt.items:
            parts.append(item.context_expr)
            if item.optional_vars is not None:
                parts.append(item.optional_vars)
        return parts
    if isinstance(stmt, ast.Try):
        return []
    return [stmt]


# ---------------------------------------------------------------------------
# statement-level CFG
# ---------------------------------------------------------------------------


class CFG:
    """Control-flow graph over one function body, one node per statement.

    ``succ[i]`` is the set of statement indices execution may continue to
    after statement ``i``; loop bodies edge back to their header, so a
    path "around the loop" exists for reaching-uses queries."""

    def __init__(self, fn: FunctionNode):
        self.stmts: List[ast.stmt] = []
        self.succ: Dict[int, Set[int]] = {}
        body = fn.body if isinstance(fn.body, list) else []
        self.entry: Set[int] = set()
        exits = self._block(body, preds=set(), loops=[], entry=True)
        self.exits: Set[int] = exits

    def _add(self, stmt: ast.stmt) -> int:
        i = len(self.stmts)
        self.stmts.append(stmt)
        self.succ[i] = set()
        return i

    def _block(
        self,
        stmts: Sequence[ast.stmt],
        preds: Set[int],
        loops: List[Tuple[int, List[int]]],
        entry: bool = False,
    ) -> Set[int]:
        for stmt in stmts:
            i = self._add(stmt)
            if entry:
                self.entry.add(i)
                entry = False
            for p in preds:
                self.succ[p].add(i)
            preds = self._stmt(stmt, i, loops)
            if not preds:
                break  # everything after return/raise/break is unreachable
        return preds

    def _stmt(
        self, stmt: ast.stmt, i: int, loops: List[Tuple[int, List[int]]]
    ) -> Set[int]:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            return set()
        if isinstance(stmt, ast.Break):
            if loops:
                loops[-1][1].append(i)
            return set()
        if isinstance(stmt, ast.Continue):
            if loops:
                self.succ[i].add(loops[-1][0])
            return set()
        if isinstance(stmt, ast.If):
            then_exits = self._block(stmt.body, {i}, loops)
            else_exits = self._block(stmt.orelse, {i}, loops) if stmt.orelse else {i}
            return then_exits | else_exits
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            breaks: List[int] = []
            loops.append((i, breaks))
            body_exits = self._block(stmt.body, {i}, loops)
            loops.pop()
            for p in body_exits:
                self.succ[p].add(i)  # loop back edge
            exits = {i}
            if stmt.orelse:
                exits = self._block(stmt.orelse, exits, loops)
            return exits | set(breaks)
        if isinstance(stmt, ast.Try):
            first_body = len(self.stmts)
            body_exits = self._block(stmt.body, {i}, loops)
            body_nodes = set(range(first_body, len(self.stmts)))
            handler_exits: Set[int] = set()
            for handler in stmt.handlers:
                # any body statement may raise into the handler
                handler_exits |= self._block(
                    handler.body, {i} | body_nodes, loops
                )
            else_exits = (
                self._block(stmt.orelse, body_exits, loops)
                if stmt.orelse
                else body_exits
            )
            merged = else_exits | handler_exits
            if stmt.finalbody:
                merged = self._block(stmt.finalbody, merged, loops)
            return merged
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._block(stmt.body, {i}, loops)
        if isinstance(stmt, ast.Match):
            exits: Set[int] = {i}  # no case may match: fall through
            for case in stmt.cases:
                exits |= self._block(case.body, {i}, loops)
            return exits
        return {i}


# ---------------------------------------------------------------------------
# FunctionFlow: CFG + def/use + poison propagation
# ---------------------------------------------------------------------------


class FunctionFlow:
    """Def-use view of one function body, queryable by rules.

    ``reads(i)``/``writes(i)`` are the names statement ``i``'s own CFG node
    loads and stores; :meth:`reaching_uses` is the poison query the
    use-after-donate rule runs after every donating dispatch."""

    def __init__(self, fn: FunctionNode):
        self.fn = fn
        self.cfg = CFG(fn)
        self._reads: List[Set[str]] = []
        self._writes: List[Set[str]] = []
        self._stmt_of: Dict[int, int] = {}  # id(descendant) -> stmt index
        for i, stmt in enumerate(self.cfg.stmts):
            reads: Set[str] = set()
            writes: Set[str] = set()
            for part in _own_parts(stmt):
                _collect(part, reads, writes)
                for node in ast.walk(part):
                    self._stmt_of.setdefault(id(node), i)
            self._reads.append(reads)
            self._writes.append(writes)
            self._stmt_of.setdefault(id(stmt), i)

    def reads(self, i: int) -> Set[str]:
        """Names statement ``i`` loads."""
        return self._reads[i]

    def writes(self, i: int) -> Set[str]:
        """Names statement ``i`` stores (a poison kill)."""
        return self._writes[i]

    def statement_of(self, node: ast.AST) -> Optional[int]:
        """The CFG statement index whose own node contains ``node``."""
        return self._stmt_of.get(id(node))

    def reaching_uses(self, start: int, name: str) -> List[ast.stmt]:
        """Statements reading ``name`` on some CFG path after ``start``
        before any redefinition — line-sorted, each statement once.

        The start statement itself is excluded, but remains reachable
        through a loop back edge: an un-rebound name consumed again on the
        next iteration is exactly the donate bug this exists to find.
        Callers must first check ``name in writes(start)`` — a statement
        that rebinds the name (``params, opt = step(params, opt)``) kills
        its own poison before any successor runs."""
        hits: Dict[int, ast.stmt] = {}
        seen: Set[int] = set()
        work = list(self.cfg.succ.get(start, ()))
        while work:
            i = work.pop()
            if i in seen:
                continue
            seen.add(i)
            if name in self._reads[i]:
                hits[i] = self.cfg.stmts[i]
            if name in self._writes[i]:
                continue  # redefined: poison dead past this statement
            work.extend(self.cfg.succ.get(i, ()))
        return sorted(hits.values(), key=lambda s: (s.lineno, s.col_offset))


# ---------------------------------------------------------------------------
# taint propagation with provenance chains
# ---------------------------------------------------------------------------

#: Calls whose presence in an expression marks the value process-unique —
#: the atomic tmp-file idiom's discriminator.
_PID_UNIQUE_CALLEES = {
    "os.getpid", "getpid", "uuid.uuid4", "uuid4",
    "tempfile.mkstemp", "mkstemp",
    "tempfile.NamedTemporaryFile", "NamedTemporaryFile",
}


@dataclass(frozen=True)
class Taint:
    """Why a value is tainted: a provenance chain of (line, description)
    hops from the seed to the expression at hand, plus whether the value
    is process-unique (contains a getpid/mkstemp/uuid component)."""

    chain: Tuple[Tuple[int, str], ...]
    pid_unique: bool = False

    def extend(self, line: int, desc: str) -> "Taint":
        """A new hop appended (chains are capped so messages stay short)."""
        chain = self.chain if len(self.chain) >= 6 else self.chain + ((line, desc),)
        return Taint(chain=chain, pid_unique=self.pid_unique)

    def render(self) -> str:
        """``def site -> hop -> hop`` text for finding messages."""
        return " -> ".join(f"{desc} (line {line})" for line, desc in self.chain)


#: A seed callback: non-None description when the expression node itself
#: originates taint (e.g. "reads $TIP_OBS_INDEX", "literal 'journal' path").
SeedFn = Callable[[ast.AST], Optional[str]]

#: A call-effect callback: Taint for a call's return value, given the call
#: node and a resolver for argument taint (interprocedural summaries).
CallFn = Callable[[ast.Call, Callable[[ast.AST], Optional[Taint]]], Optional[Taint]]


def _pid_unique_expr(expr: ast.AST, aliases: Dict[str, str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = callee_name(node, aliases)
            if name in _PID_UNIQUE_CALLEES:
                return True
    return False


class TaintEnv:
    """Name -> Taint environment for one function (or module) body.

    Flow-insensitive fixed point: a name is tainted when any assignment
    reachable in the body binds it to a tainted expression. Taint flows
    through f-strings, concatenation, ``os.path.join`` (any call's
    arguments taint its result — path helpers are pass-through), tuple
    unpacking, and the optional ``call_effect`` interprocedural summary."""

    def __init__(
        self,
        fn_body: Sequence[ast.stmt],
        aliases: Dict[str, str],
        seed: SeedFn,
        call_effect: Optional[CallFn] = None,
        param_taints: Optional[Dict[str, Taint]] = None,
    ):
        self._aliases = aliases
        self._seed = seed
        self._call_effect = call_effect
        self.names: Dict[str, Taint] = dict(param_taints or {})
        assigns = self._assignments(fn_body)
        for _ in range(8):  # fixed point; chains are capped so this converges
            changed = False
            for targets, value in assigns:
                taint = self.expr_taint(value)
                if taint is None:
                    continue
                for target in targets:
                    changed |= self._bind(target, value, taint)
            if not changed:
                break

    def _assignments(
        self, body: Sequence[ast.stmt]
    ) -> List[Tuple[List[ast.expr], ast.expr]]:
        out: List[Tuple[List[ast.expr], ast.expr]] = []
        for stmt in body:
            for node in scope_walk(stmt):
                if isinstance(node, ast.Assign):
                    out.append((list(node.targets), node.value))
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    out.append(([node.target], node.value))
                elif isinstance(node, ast.AugAssign):
                    out.append(([node.target], node.value))
                elif isinstance(node, ast.NamedExpr):
                    out.append(([node.target], node.value))
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if item.optional_vars is not None:
                            out.append(
                                ([item.optional_vars], item.context_expr)
                            )
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    out.append(([node.target], node.iter))
        return out

    def _bind(self, target: ast.expr, value: ast.expr, taint: Taint) -> bool:
        changed = False
        if isinstance(target, ast.Tuple) and isinstance(value, ast.Tuple) and (
            len(target.elts) == len(value.elts)
        ):
            for t, v in zip(target.elts, value.elts):
                sub = self.expr_taint(v)
                if sub is not None:
                    changed |= self._bind(t, v, sub)
            return changed
        names: List[Tuple[str, int]] = []
        if isinstance(target, ast.Name):
            names.append((target.id, target.lineno))
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                if isinstance(el, ast.Name):
                    names.append((el.id, el.lineno))
        elif isinstance(target, ast.Attribute):
            names.append((f"<attr>{target.attr}", target.lineno))
        for name, line in names:
            if name not in self.names:
                self.names[name] = taint.extend(line, f"`{name}` =")
                changed = True
        return changed

    def expr_taint(self, expr: ast.AST) -> Optional[Taint]:
        """The Taint of an expression under the current environment."""
        taint = self._expr_taint(expr)
        if taint is not None and not taint.pid_unique:
            if _pid_unique_expr(expr, self._aliases):
                taint = Taint(chain=taint.chain, pid_unique=True)
        return taint

    def _expr_taint(self, expr: ast.AST) -> Optional[Taint]:
        if isinstance(expr, _FUNCTION_NODES):
            return None
        desc = self._seed(expr)
        if desc is not None:
            return Taint(chain=((getattr(expr, "lineno", 0), desc),))
        if isinstance(expr, ast.Name) and expr.id in self.names:
            return self.names[expr.id]
        if isinstance(expr, ast.Attribute):
            key = f"<attr>{expr.attr}"
            if key in self.names:
                return self.names[key]
        if isinstance(expr, ast.Call) and self._call_effect is not None:
            taint = self._call_effect(expr, self.expr_taint)
            if taint is not None:
                return taint.extend(
                    expr.lineno, f"{callee_name(expr, self._aliases) or 'call'}()"
                )
        for child in ast.iter_child_nodes(expr):
            taint = self._expr_taint(child)
            if taint is not None:
                return taint
        return None


# ---------------------------------------------------------------------------
# env-read detection (shared by knob-contract and the bus seeds)
# ---------------------------------------------------------------------------


def environ_alias_names(tree: ast.Module) -> Set[str]:
    """Local names bound to ``os.environ`` via ``from os import environ``."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for alias in node.names:
                if alias.name == "environ":
                    names.add(alias.asname or "environ")
    return names


def _is_environ(node: ast.AST, environ_names: Set[str]) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return isinstance(node.value, ast.Name) and node.value.id == "os"
    return isinstance(node, ast.Name) and node.id in environ_names


def env_read_key(
    node: ast.AST, aliases: Dict[str, str], environ_names: Set[str]
) -> Optional[ast.expr]:
    """The key expression when ``node`` reads ``os.environ`` — covers
    ``os.environ.get(K)``, ``os.environ.setdefault(K, d)`` (a read too),
    ``os.getenv(K)`` and ``os.environ[K]`` loads — else None."""
    if isinstance(node, ast.Call):
        name = callee_name(node, aliases)
        if name in ("os.getenv", "getenv") and node.args:
            return node.args[0]
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "setdefault")
            and _is_environ(node.func.value, environ_names)
            and node.args
        ):
            return node.args[0]
    if (
        isinstance(node, ast.Subscript)
        and isinstance(node.ctx, ast.Load)
        and _is_environ(node.value, environ_names)
    ):
        return node.slice
    return None


@dataclass(frozen=True)
class EnvRead:
    """One literal env-var read, possibly through a helper call chain."""

    module: ModuleInfo
    line: int
    env: str
    via: str = ""  # "" for a direct read; helper dotted name otherwise


# ---------------------------------------------------------------------------
# ProjectFlow: interprocedural stitching over the project graph
# ---------------------------------------------------------------------------


@dataclass(eq=False)
class _FnSummary:
    """Interprocedural facts about one project function."""

    env_params: Set[str] = field(default_factory=set)  # params read as env keys
    returns_seeded: bool = False  # return value tainted by in-body seeds


class ProjectFlow:
    """Dataflow layer over one run's :class:`ProjectGraph`.

    Summaries are computed to a fixed point over the graph's call edges
    (including the ``partial``-binding and ``self.``-method edges), so a
    helper two hops from the env read or the bus seed still carries the
    fact to its call sites."""

    def __init__(self, modules: Sequence[ModuleInfo]):
        self.modules = modules
        self.graph: ProjectGraph = project_graph(modules)
        self._flows: Dict[int, FunctionFlow] = {}
        self._aliases: Dict[int, Dict[str, str]] = {}
        self._environ_names: Dict[int, Set[str]] = {}
        self._parents: Dict[int, Dict[ast.AST, ast.AST]] = {}
        self._env_reads: Optional[List[EnvRead]] = None

    # -- per-module memos --------------------------------------------------

    def flow(self, fn: FunctionNode) -> FunctionFlow:
        """The (cached) FunctionFlow of a function node."""
        key = id(fn)
        if key not in self._flows:
            self._flows[key] = FunctionFlow(fn)
        return self._flows[key]

    def aliases(self, module: ModuleInfo) -> Dict[str, str]:
        """The module's import aliases (cached)."""
        key = id(module)
        if key not in self._aliases:
            self._aliases[key] = import_aliases(module.tree)
        return self._aliases[key]

    def environ_names(self, module: ModuleInfo) -> Set[str]:
        """Local ``os.environ`` aliases of a module (cached)."""
        key = id(module)
        if key not in self._environ_names:
            self._environ_names[key] = environ_alias_names(module.tree)
        return self._environ_names[key]

    def parents(self, module: ModuleInfo) -> Dict[ast.AST, ast.AST]:
        """child -> parent map of a module tree (cached)."""
        key = id(module)
        if key not in self._parents:
            self._parents[key] = parent_map(module.tree)
        return self._parents[key]

    def enclosing_function(
        self, module: ModuleInfo, node: ast.AST
    ) -> Optional[FunctionNode]:
        """The innermost function/lambda containing ``node``, or None."""
        parents = self.parents(module)
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, _FUNCTION_NODES):
                return cur
            cur = parents.get(cur)
        return None

    def functions_of(self, module: ModuleInfo) -> List[FunctionInfo]:
        """The graph's FunctionInfos defined in ``module``."""
        return [
            fi for fi in self.graph.functions.values() if fi.module is module
        ]

    # -- call-site argument binding ---------------------------------------

    @staticmethod
    def bind_args(call: ast.Call, fi: FunctionInfo) -> Dict[str, ast.expr]:
        """param name -> argument expression for a resolvable call site.

        Bound-method calls (``self.helper(...)``, any ``Class.method``
        target called through an attribute) skip the ``self``/``cls``
        slot. ``*args``/``**kwargs`` at the call site end the positional
        mapping (unknown arity beyond that point)."""
        params = lambda_or_def_params(fi.node)
        if (
            params
            and params[0] in ("self", "cls")
            and "." in fi.qualname
            and isinstance(call.func, ast.Attribute)
        ):
            params = params[1:]
        bound: Dict[str, ast.expr] = {}
        for pos, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred) or pos >= len(params):
                break
            bound[params[pos]] = arg
        for kw in call.keywords:
            if kw.arg is not None:
                bound[kw.arg] = kw.value
        return bound

    # -- interprocedural env reads (knob-contract) -------------------------

    def env_reads(self) -> List[EnvRead]:
        """Every literal env-name read in the project, direct or through a
        helper whose parameter is the key (``_env("TIP_X", ...)`` counts as
        a read of ``TIP_X`` at the call site). Computed once per run."""
        if self._env_reads is not None:
            return self._env_reads
        reads: List[EnvRead] = []
        summaries: Dict[int, _FnSummary] = {}

        # pass 1: direct reads; params used as keys seed the summaries
        for module in self.modules:
            aliases = self.aliases(module)
            environ_names = self.environ_names(module)
            for node in ast.walk(module.tree):
                key = env_read_key(node, aliases, environ_names)
                if key is None:
                    continue
                literal = self.graph.resolve_string(module, key)
                if literal is not None:
                    reads.append(
                        EnvRead(module=module, line=node.lineno, env=literal)
                    )
                    continue
                if isinstance(key, ast.Name):
                    fn = self.enclosing_function(module, node)
                    if fn is not None and key.id in lambda_or_def_params(fn):
                        summaries.setdefault(id(fn), _FnSummary()).env_params.add(
                            key.id
                        )

        seen_calls: Set[Tuple[int, str]] = set()

        # pass 1b: closure helpers — a nested def is not a project-graph
        # function, so calls to a summarized local helper are resolved by
        # name inside the enclosing function's own scope
        # (``_num("TIP_BREAKER_THRESHOLD", 2)`` inside ``from_env``).
        # A key that is the *outer* function's parameter seeds the outer
        # summary, feeding the graph-wide fixed point below.
        for module in self.modules:
            for outer in ast.walk(module.tree):
                if not isinstance(
                    outer, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                helpers = {
                    name: fn
                    for name, fn in nested_defs(outer).items()
                    if id(fn) in summaries and summaries[id(fn)].env_params
                }
                if not helpers:
                    continue
                outer_params = lambda_or_def_params(outer)
                for stmt in outer.body:
                    for node in scope_walk(stmt):
                        if not (
                            isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Name)
                            and node.func.id in helpers
                        ):
                            continue
                        helper = helpers[node.func.id]
                        params = lambda_or_def_params(helper)
                        bound: Dict[str, ast.expr] = {}
                        for pos, arg in enumerate(node.args):
                            if isinstance(arg, ast.Starred) or pos >= len(
                                params
                            ):
                                break
                            bound[params[pos]] = arg
                        for kw in node.keywords:
                            if kw.arg is not None:
                                bound[kw.arg] = kw.value
                        for param in sorted(
                            summaries[id(helper)].env_params
                        ):
                            arg = bound.get(param)
                            if arg is None:
                                continue
                            literal = self.graph.resolve_string(module, arg)
                            if literal is not None:
                                mark = (id(node), literal)
                                if mark not in seen_calls:
                                    seen_calls.add(mark)
                                    reads.append(
                                        EnvRead(
                                            module=module,
                                            line=node.lineno,
                                            env=literal,
                                            via=node.func.id,
                                        )
                                    )
                            elif (
                                isinstance(arg, ast.Name)
                                and arg.id in outer_params
                            ):
                                summaries.setdefault(
                                    id(outer), _FnSummary()
                                ).env_params.add(arg.id)

        # pass 2: propagate key-parameters through call sites to a fixed
        # point, recording literal arguments as reads where they are passed
        for _ in range(6):
            changed = False
            for module in self.modules:
                for fi in self.functions_of(module):
                    for call, callee in self.graph.calls_from(module, fi.node):
                        summary = summaries.get(id(callee.node))
                        if summary is None or not summary.env_params:
                            continue
                        bound = self.bind_args(call, callee)
                        for param in sorted(summary.env_params):
                            arg = bound.get(param)
                            if arg is None:
                                continue
                            literal = self.graph.resolve_string(module, arg)
                            if literal is not None:
                                mark = (id(call), literal)
                                if mark not in seen_calls:
                                    seen_calls.add(mark)
                                    reads.append(
                                        EnvRead(
                                            module=module,
                                            line=call.lineno,
                                            env=literal,
                                            via=callee.dotted,
                                        )
                                    )
                                    changed = True
                            elif isinstance(arg, ast.Name) and arg.id in (
                                lambda_or_def_params(fi.node)
                            ):
                                s = summaries.setdefault(
                                    id(fi.node), _FnSummary()
                                )
                                if arg.id not in s.env_params:
                                    s.env_params.add(arg.id)
                                    changed = True
            if not changed:
                break
        self._env_reads = reads
        return reads

    # -- interprocedural seed summaries (unsafe-bus-write) -----------------

    def seeded_return_summaries(self, seed_for: Callable[[ModuleInfo], SeedFn]) -> Dict[int, bool]:
        """id(FunctionNode) -> "its return value is tainted by in-body
        seeds", iterated so seeded helpers taint their callers' returns.

        ``seed_for(module)`` builds the per-module seed callback (seeds are
        alias-dependent). Argument pass-through needs no summary: the taint
        engine already taints any call whose argument is tainted."""
        summaries: Dict[int, bool] = {}
        for _ in range(4):
            changed = False
            for module in self.modules:
                seed = seed_for(module)
                aliases = self.aliases(module)
                for fi in self.functions_of(module):
                    if summaries.get(id(fi.node)):
                        continue

                    def call_effect(call, _arg_taint, _module=module):
                        name = callee_name(call, self.aliases(_module))
                        target = (
                            self.graph.resolve_function(_module, name)
                            if name
                            else None
                        )
                        if target is not None and summaries.get(id(target.node)):
                            return Taint(
                                chain=((call.lineno, f"{name}() returns bus path"),)
                            )
                        return None

                    body = (
                        fi.node.body
                        if isinstance(fi.node.body, list)
                        else [ast.Expr(value=fi.node.body)]
                    )
                    env = TaintEnv(body, aliases, seed, call_effect)
                    for stmt in body:
                        for node in ast.walk(stmt):
                            if isinstance(node, ast.Return) and node.value is not None:
                                if env.expr_taint(node.value) is not None:
                                    summaries[id(fi.node)] = True
                                    changed = True
                                    break
                        if summaries.get(id(fi.node)):
                            break
            if not changed:
                break
        return summaries


#: (module list, flow) of the most recent build — the same identity cache
#: discipline as graph.project_graph, so the four dataflow rules share one
#: ProjectFlow (and its memoized FunctionFlows) per analyzer run.
_LAST_FLOW: Optional[Tuple[Sequence[ModuleInfo], ProjectFlow]] = None


def project_flow(modules: Sequence[ModuleInfo]) -> ProjectFlow:
    """The per-run cached ProjectFlow for a module set."""
    global _LAST_FLOW
    if _LAST_FLOW is None or _LAST_FLOW[0] is not modules:
        _LAST_FLOW = (modules, ProjectFlow(modules))
    return _LAST_FLOW[1]


# ---------------------------------------------------------------------------
# shared helpers for the dataflow rules
# ---------------------------------------------------------------------------


def iter_function_nodes(tree: ast.Module) -> Iterator[FunctionNode]:
    """Every def/lambda in a module, outermost first."""
    for node in ast.walk(tree):
        if isinstance(node, _FUNCTION_NODES):
            yield node


_BUS_SEGMENT_RE = re.compile(
    r"(?:^|/)(journal|sa_fit_cache|program_cache|leases|heartbeats)(?:/|$)"
    r"|(?:^|/)(runs\.jsonl|index\.jsonl|manifest\.json)$"
)

_BUS_IDENT_RE = re.compile(
    r"journal|sa_fit|sa_cache|program_cache|lease|heartbeat"
    r"|manifest_path|rows_path|index_dir"
)

#: Env vars that *are* a shared-bus root: a path read from one of these is
#: bus-derived by definition.
BUS_ENV_VARS = frozenset(
    {
        "TIP_JOURNAL",
        "TIP_SA_CACHE_DIR",
        "TIP_PROGRAM_CACHE_DIR",
        "TIP_OBS_INDEX",
        "TIP_COV_STATS_CACHE_DIR",
        "TIP_BREAKER_STATE",
        "TIP_FLEET_HOST",
    }
)


def bus_seed(module: ModuleInfo, flow: ProjectFlow) -> SeedFn:
    """The unsafe-bus-write seed callback for one module: env reads of bus
    roots, path literals with a bus segment, and identifiers that *name* a
    bus artifact (``manifest_path``, ``self.journal``, ...)."""
    aliases = flow.aliases(module)
    environ_names = flow.environ_names(module)

    def seed(node: ast.AST) -> Optional[str]:
        key = env_read_key(node, aliases, environ_names)
        if key is not None:
            literal = flow.graph.resolve_string(module, key)
            if literal in BUS_ENV_VARS:
                return f"bus root ${literal}"
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            m = _BUS_SEGMENT_RE.search(node.value.replace("\\", "/"))
            if m:
                seg = m.group(1) or m.group(2)
                return f"bus path literal {node.value!r} ({seg})"
        ident = None
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        if ident is not None and _BUS_IDENT_RE.fullmatch(ident) is None:
            # full-identifier heuristics only for exact bus names; substring
            # matches (e.g. `release_fn`) would be noise
            if _BUS_IDENT_RE.search(ident) and (
                ident.endswith(("_path", "_dir", "_file"))
                or ident in ("journal", "lease", "heartbeat")
            ):
                return f"bus artifact `{ident}`"
            return None
        if ident is not None:
            return f"bus artifact `{ident}`"
        return None

    return seed

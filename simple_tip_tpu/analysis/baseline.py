"""tiplint baselines: adopt the analyzer on a codebase with prior debt.

A baseline file records the *accepted* findings of some reference run as
line-insensitive fingerprints — ``rule|path|message`` with a count — so a
tree that moves code around (shifting line numbers) keeps its accepted
debt accepted, while any **new** finding (new rule hit, new message, or
one more occurrence of an old one) still fails the run.

``--write-baseline`` snapshots the current unsuppressed findings;
``--baseline`` re-marks covered findings as suppressed before reporting,
so every reporter (text/json/github/sarif) shows them as carried debt
rather than failures. The committed ``tiplint_baseline.json`` at the repo
root is intentionally empty: the sweep is clean today, and the file
existing keeps the adoption path one flag away when a future rule lands
with unpayable debt.
"""

import json
import os
from collections import Counter
from dataclasses import replace
from typing import Dict, List, Sequence, Tuple

from simple_tip_tpu.analysis.core import Finding

_SCHEMA = 1


def fingerprint(finding: Finding) -> str:
    """The line-insensitive identity of a finding (``rule|path|message``)."""
    return f"{finding.rule}|{finding.path}|{finding.message}"


def write_baseline(path: str, findings: Sequence[Finding]) -> int:
    """Snapshot the unsuppressed findings into ``path``; returns the count.

    Published atomically (pid-unique tmp + replace) and serialized with
    sorted keys so two identical runs write byte-identical baselines.
    """
    counts = Counter(
        fingerprint(f) for f in findings if not f.suppressed
    )
    doc = {"schema": _SCHEMA, "fingerprints": dict(sorted(counts.items()))}
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return sum(counts.values())


def load_baseline(path: str) -> Dict[str, int]:
    """fingerprint -> accepted count; raises ValueError on a bad file."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("schema") != _SCHEMA:
        raise ValueError(f"{path}: not a tiplint baseline (schema {_SCHEMA})")
    fps = doc.get("fingerprints")
    if not isinstance(fps, dict):
        raise ValueError(f"{path}: baseline has no fingerprint table")
    return {str(k): int(v) for k, v in fps.items()}


def apply_baseline(
    findings: Sequence[Finding], accepted: Dict[str, int]
) -> Tuple[List[Finding], int]:
    """Re-mark baseline-covered findings as suppressed.

    Each fingerprint covers up to its accepted count (first occurrences in
    the driver's deterministic sort order win — the stable choice). Returns
    (findings, how many were covered).
    """
    budget = dict(accepted)
    out: List[Finding] = []
    covered = 0
    for f in findings:
        if not f.suppressed:
            fp = fingerprint(f)
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1
                covered += 1
                out.append(replace(f, suppressed=True, baselined=True))
                continue
        out.append(f)
    return out, covered

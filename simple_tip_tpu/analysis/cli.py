"""tiplint command line: ``python -m simple_tip_tpu.analysis [paths...]``.

Exit status is the contract consumed by scripts/lint.sh and CI: 0 when every
finding is suppressed (or there are none), 1 when unsuppressed findings
remain, 2 on usage errors.

Beyond the core sweep the CLI owns three workflow modes:

- ``--baseline FILE`` / ``--write-baseline FILE`` — adopt-with-debt: accept
  a recorded set of findings (line-insensitive fingerprints) as suppressed
  (``analysis/baseline.py``);
- ``--changed-only [REF]`` — scope reporting to files git considers changed
  against REF (default HEAD) plus untracked files; the whole tree is still
  parsed so project-graph and dataflow rules see the full program;
- ``--cache DIR`` (or ``$TIPLINT_CACHE``) — reuse a prior identical run's
  findings when no analyzed file and no analyzer source changed
  (``analysis/cache.py``); announced on stderr, bypassed under
  ``--changed-only`` (scoped runs are cheap and git state isn't keyed).
"""

import argparse
import os
import subprocess
import sys
from typing import List, Optional, Set

from simple_tip_tpu.analysis.core import all_rules, analyze_paths, unsuppressed
from simple_tip_tpu.analysis.reporters import REPORTERS, render


def _default_target() -> str:
    """The installed ``simple_tip_tpu`` package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def build_parser() -> argparse.ArgumentParser:
    """The tiplint argument parser (exposed for --help doc tooling)."""
    parser = argparse.ArgumentParser(
        prog="python -m simple_tip_tpu.analysis",
        description=(
            "tiplint: JAX/TPU-aware static analysis for simple_tip_tpu "
            "(jit purity, PRNG hygiene, host syncs, f64-on-TPU, buffer "
            "donation, artifact contract, docstring coverage, the "
            "project-graph rules: sharding-spec-mismatch, "
            "shape-polymorphism, transitive-jit-purity, and the dataflow "
            "rules: use-after-donate, escaping-tracer, unsafe-bus-write, "
            "knob-contract)."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files or directories to analyze (default: the simple_tip_tpu package)",
    )
    parser.add_argument(
        "--format",
        choices=sorted(REPORTERS),
        default="text",
        help="report format (default: text)",
    )
    parser.add_argument(
        "--select",
        default=None,
        help="comma-separated rule names to run (default: all rules)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the registered rules and exit",
    )
    parser.add_argument(
        "--baseline",
        metavar="FILE",
        default=None,
        help=(
            "accept findings recorded in this baseline file as suppressed "
            "(line-insensitive rule|path|message fingerprints)"
        ),
    )
    parser.add_argument(
        "--write-baseline",
        metavar="FILE",
        default=None,
        help=(
            "write the current unsuppressed findings as a baseline file "
            "and exit 0 (the adopt-with-debt snapshot)"
        ),
    )
    parser.add_argument(
        "--changed-only",
        nargs="?",
        const="HEAD",
        default=None,
        metavar="REF",
        help=(
            "report only findings in files changed vs REF (default HEAD) "
            "per git, plus untracked files; the full tree is still parsed "
            "so cross-file rules keep whole-program context"
        ),
    )
    parser.add_argument(
        "--cache",
        metavar="DIR",
        default=os.environ.get("TIPLINT_CACHE") or None,
        help=(
            "findings cache directory (default: $TIPLINT_CACHE); a re-run "
            "with unchanged inputs and unchanged analyzer source replays "
            "the stored findings byte-identically"
        ),
    )
    return parser


def _changed_files(paths: List[str], ref: str) -> Optional[Set[str]]:
    """Absolute paths of .py files changed vs ``ref`` (plus untracked),
    or None when git can't answer (not a repo / bad ref)."""
    anchor = paths[0]
    cwd = anchor if os.path.isdir(anchor) else os.path.dirname(anchor)
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=cwd, capture_output=True, text=True, timeout=30,
        )
        if top.returncode != 0:
            return None
        root = top.stdout.strip()
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        if untracked.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    out: Set[str] = set()
    for line in diff.stdout.splitlines() + untracked.stdout.splitlines():
        line = line.strip()
        if line.endswith(".py"):
            out.add(os.path.abspath(os.path.join(root, line)))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    """Entry point; returns the process exit status."""
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for name, rule in sorted(all_rules().items()):
            tags = f" [{', '.join(rule.tags)}]" if rule.tags else ""
            print(f"{name}{tags}: {rule.description}")
        return 0

    paths = args.paths or [_default_target()]
    for p in paths:
        if not os.path.exists(p):
            print(f"tiplint: no such path: {p}", file=sys.stderr)
            return 2
    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]

    only_paths: Optional[Set[str]] = None
    if args.changed_only is not None:
        only_paths = _changed_files(paths, args.changed_only)
        if only_paths is None:
            print(
                f"tiplint: --changed-only: git could not diff against "
                f"{args.changed_only!r} (not a repository, or unknown ref)",
                file=sys.stderr,
            )
            return 2

    use_cache = args.cache if only_paths is None else None
    cache_key = None
    findings = None
    if use_cache:
        from simple_tip_tpu.analysis import cache as _cache

        cache_key = _cache.run_key(paths, select)
        findings = _cache.load(use_cache, cache_key)
        if findings is not None:
            print(f"tiplint: cache hit ({cache_key[:12]})", file=sys.stderr)

    if findings is None:
        try:
            findings = analyze_paths(paths, select=select, only_paths=only_paths)
        except KeyError as exc:
            print(f"tiplint: {exc.args[0]}", file=sys.stderr)
            return 2
        if use_cache and cache_key is not None:
            from simple_tip_tpu.analysis import cache as _cache

            _cache.store(use_cache, cache_key, findings)

    if args.write_baseline:
        from simple_tip_tpu.analysis.baseline import write_baseline

        count = write_baseline(args.write_baseline, findings)
        print(
            f"tiplint: wrote baseline {args.write_baseline} "
            f"({count} accepted finding(s))",
            file=sys.stderr,
        )
        return 0

    if args.baseline:
        from simple_tip_tpu.analysis.baseline import (
            apply_baseline,
            load_baseline,
        )

        try:
            accepted = load_baseline(args.baseline)
        except (OSError, ValueError) as exc:
            print(f"tiplint: --baseline: {exc}", file=sys.stderr)
            return 2
        findings, covered = apply_baseline(findings, accepted)
        if covered:
            print(
                f"tiplint: {covered} finding(s) covered by baseline "
                f"{args.baseline}",
                file=sys.stderr,
            )

    try:
        print(render(findings, args.format))
        sys.stdout.flush()
    except BrokenPipeError:
        # Downstream pager/head closed the pipe; the analysis still ran, so
        # keep the finding-based exit status instead of tracebacking. Point
        # stdout at devnull so the interpreter's shutdown flush stays quiet.
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
    return 1 if unsuppressed(findings) else 0

"""tiplint command line: ``python -m simple_tip_tpu.analysis [paths...]``.

Exit status is the contract consumed by scripts/lint.sh and CI: 0 when every
finding is suppressed (or there are none), 1 when unsuppressed findings
remain, 2 on usage errors.
"""

import argparse
import os
import sys
from typing import List, Optional

from simple_tip_tpu.analysis.core import all_rules, analyze_paths, unsuppressed
from simple_tip_tpu.analysis.reporters import REPORTERS, render


def _default_target() -> str:
    """The installed ``simple_tip_tpu`` package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def build_parser() -> argparse.ArgumentParser:
    """The tiplint argument parser (exposed for --help doc tooling)."""
    parser = argparse.ArgumentParser(
        prog="python -m simple_tip_tpu.analysis",
        description=(
            "tiplint: JAX/TPU-aware static analysis for simple_tip_tpu "
            "(jit purity, PRNG hygiene, host syncs, f64-on-TPU, buffer "
            "donation, artifact contract, docstring coverage, and the "
            "project-graph rules: sharding-spec-mismatch, "
            "shape-polymorphism, transitive-jit-purity)."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files or directories to analyze (default: the simple_tip_tpu package)",
    )
    parser.add_argument(
        "--format",
        choices=sorted(REPORTERS),
        default="text",
        help="report format (default: text)",
    )
    parser.add_argument(
        "--select",
        default=None,
        help="comma-separated rule names to run (default: all rules)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print the registered rules and exit",
    )
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    """Entry point; returns the process exit status."""
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for name, rule in sorted(all_rules().items()):
            print(f"{name}: {rule.description}")
        return 0

    paths = args.paths or [_default_target()]
    for p in paths:
        if not os.path.exists(p):
            print(f"tiplint: no such path: {p}", file=sys.stderr)
            return 2
    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
    try:
        findings = analyze_paths(paths, select=select)
    except KeyError as exc:
        print(f"tiplint: {exc.args[0]}", file=sys.stderr)
        return 2
    try:
        print(render(findings, args.format))
        sys.stdout.flush()
    except BrokenPipeError:
        # Downstream pager/head closed the pipe; the analysis still ran, so
        # keep the finding-based exit status instead of tracebacking. Point
        # stdout at devnull so the interpreter's shutdown flush stays quiet.
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
    return 1 if unsuppressed(findings) else 0

"""Rule ``blocking-endpoint``: slow work inside HTTP handler bodies.

The obs exporter's contract (obs/exporter.py) is push-model: handler
threads serve ONLY the in-memory state the owning loops already pushed —
the metrics registry, the ``set_health`` dict, provider callables
returning cached views. The moment a handler body walks the filesystem,
probes a flock, shells out, or touches jax, a ``curl /healthz`` during an
outage inherits the very stall it exists to report (the journal wedge
probe blocking a health scrape is the canonical self-own), and a scrape
storm multiplies disk traffic by request rate. Filesystem-backed inputs
belong on the scheduler/fleet loops' cadence, pushed in via
``exporter.set_health`` / ``set_provider``.

Flagged lexically inside handler method bodies — methods named ``do_*``
of any class whose base-name mentions ``HTTPRequestHandler``, plus their
sibling helpers those classes define — skipping nested ``def``/lambda
scopes (their bodies execute elsewhere):

- builtin ``open(...)`` and ``os.{listdir,scandir,walk,stat,lstat,
  remove,unlink,rename,replace,makedirs}`` — filesystem IO;
- ``glob.*`` / ``shutil.*`` / ``subprocess.*`` — tree walks and child
  processes;
- ``time.sleep(...)`` — a deliberate stall on a serving thread;
- any attribute chain rooted at ``jax`` — device work has no business on
  a health endpoint.

Exempt (same surface logic as ``bare-print``): ``scripts/``, ``tests/``,
entry-point modules, and test modules — a throwaway smoke handler may
read fixtures directly.
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.bare_print import _exempt

_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

# os functions that hit the filesystem (reads AND mutations): any of these
# on a handler thread turns a scrape into disk traffic.
_OS_FS = frozenset(
    (
        "listdir", "scandir", "walk", "stat", "lstat", "remove", "unlink",
        "rename", "replace", "makedirs", "mkdir", "rmdir", "open",
    )
)

# Modules whose every call is slow-path by construction.
_SLOW_MODULES = frozenset(("glob", "shutil", "subprocess"))


def _handler_classes(tree: ast.Module):
    """Classes that look like ``http.server`` request handlers: a base
    name mentioning ``HTTPRequestHandler``, or any ``do_*`` method."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        base_names = []
        for base in node.bases:
            if isinstance(base, ast.Name):
                base_names.append(base.id)
            elif isinstance(base, ast.Attribute):
                base_names.append(base.attr)
        if any("HTTPRequestHandler" in b for b in base_names):
            yield node
        elif any(
            isinstance(item, ast.FunctionDef) and item.name.startswith("do_")
            for item in node.body
        ):
            yield node


def _method_body_nodes(fn: ast.FunctionDef):
    """Nodes lexically in ``fn``'s body, not descending into nested
    scopes (their code runs wherever they are called, not per-request)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, _NESTED_SCOPES):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _attr_root(node: ast.Attribute) -> str:
    """Leftmost name of an attribute chain (``jax.devices`` -> ``jax``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _blocking_reason(call: ast.Call) -> str:
    """Why this call must not run on a handler thread ('' = fine)."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id == "open":
            return "sync file IO (open())"
        return ""
    if not isinstance(fn, ast.Attribute):
        return ""
    root = _attr_root(fn)
    if root == "os" and fn.attr in _OS_FS:
        return f"filesystem call (os.{fn.attr})"
    if root in _SLOW_MODULES:
        return f"slow-path call ({root}.{fn.attr})"
    if root == "time" and fn.attr == "sleep":
        return "time.sleep()"
    if root == "jax":
        return f"jax call (jax...{fn.attr})"
    return ""


@register
class BlockingEndpointRule(Rule):
    """Flag filesystem/subprocess/sleep/jax calls in HTTP handler bodies."""

    name = "blocking-endpoint"
    description = (
        "filesystem walk / subprocess / sleep / jax call inside an HTTP "
        "handler body; endpoints serve only pushed in-memory state — move "
        "the slow work onto the owning loop's cadence and push it in via "
        "exporter.set_health/set_provider (scripts/tests exempt)"
    )
    tags = ('async', 'serving', 'perf')
    rationale = (
        "An HTTP handler doing filesystem walks or subprocess calls blocks the "
        "telemetry plane; endpoints must serve pushed in-memory state only."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag blocking calls lexically inside handler method bodies."""
        if _exempt(module):
            return
        for cls in _handler_classes(module.tree):
            for item in cls.body:
                if not isinstance(item, ast.FunctionDef):
                    continue
                for node in _method_body_nodes(item):
                    if not isinstance(node, ast.Call):
                        continue
                    reason = _blocking_reason(node)
                    if reason:
                        yield "", node.lineno, (
                            f"{reason} inside HTTP handler "
                            f"{cls.name}.{item.name}: endpoint threads "
                            "serve only in-memory pushed state; do this "
                            "on the owning loop and push the result via "
                            "exporter.set_health/set_provider"
                        )

"""Rule ``unversioned-schema``: obs JSONL writers must stamp a ``schema``.

The obs subsystem persists append-only JSONL that OUTLIVES the code that
wrote it: event streams are committed as test fixtures, the feature-store
index accumulates across releases, and the trend gate reads months-old
rows. A writer that emits rows without a ``schema`` version field makes
every future format change either silently misread old rows or force a
wipe of the corpus the cost model learns from. The contract (README
"Observability", ``obs/store.py``): any module under ``obs/`` that writes
JSONL lines must stamp a ``schema`` field into what it writes — a module
top-level ``SCHEMA`` constant that appears as a ``"schema"`` key in some
dict literal (or ``rec["schema"] = ...`` store) satisfies it.

Detection is intentionally coarse but low-noise: a "JSONL write site" is a
``json.dumps(...)`` call (alias-aware) that is concatenated with a string
containing a newline, passed directly to a ``.write(...)`` /
``.writelines(...)`` sink, or joined line-wise — the repo's universal
``fh.write(json.dumps(rec) + "\\n")`` idiom. ``json.dump(doc, fh)``
(whole-document JSON) and ``print(json.dumps(doc))`` (CLI output, not a
persistent stream) are out of scope: single documents are replaced
atomically, not appended to forever.
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register


def _scoped(module: ModuleInfo) -> bool:
    """Whether ``module`` lives in an ``obs`` package (any path segment)."""
    parts = module.relpath.split("/")
    return "obs" in parts[:-1]


def _dumps_aliases(tree):
    """``(module_aliases, func_aliases)`` resolving to ``json.dumps`` here.

    Covers ``import json`` (-> ``json.dumps`` attribute calls, recorded as
    ``"json"``), ``import json as j`` and ``from json import dumps [as d]``.
    """
    module_aliases, func_aliases = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "json":
                    module_aliases.add(alias.asname or "json")
        elif isinstance(node, ast.ImportFrom) and node.module == "json":
            for alias in node.names:
                if alias.name == "dumps":
                    func_aliases.add(alias.asname or "dumps")
    return module_aliases, func_aliases


def _is_dumps_call(node, module_aliases, func_aliases) -> bool:
    """Whether ``node`` is a ``json.dumps(...)`` call under any alias."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "dumps":
        return isinstance(f.value, ast.Name) and f.value.id in module_aliases
    return isinstance(f, ast.Name) and f.id in func_aliases


def _newline_str(node) -> bool:
    """Whether ``node`` is a string constant containing a newline."""
    return (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and "\n" in node.value
    )


def _jsonl_write_sites(tree, module_aliases, func_aliases):
    """Line numbers where a ``json.dumps`` result becomes a JSONL line.

    Sites: ``dumps(...) + "...\\n"`` (either operand order), ``dumps(...)``
    as a direct argument of a ``.write(...)``/``.writelines(...)`` sink,
    and ``"\\n".join(... dumps ...)``.
    """
    sites = []
    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            pairs = ((node.left, node.right), (node.right, node.left))
            for dumps_side, str_side in pairs:
                if _is_dumps_call(
                    dumps_side, module_aliases, func_aliases
                ) and _newline_str(str_side):
                    sites.append(node.lineno)
                    break
        elif isinstance(node, ast.Call):
            is_write_sink = (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("write", "writelines")
            )
            if is_write_sink:
                for arg in node.args:
                    if _is_dumps_call(arg, module_aliases, func_aliases):
                        sites.append(node.lineno)
                        break
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and _newline_str(node.func.value)
            ):
                for sub in ast.walk(node):
                    if _is_dumps_call(sub, module_aliases, func_aliases):
                        sites.append(node.lineno)
                        break
    return sites


def _stamps_schema(tree) -> bool:
    """Whether the module ever writes a ``"schema"`` key into a dict.

    Accepts a ``"schema"`` key in any dict literal, a ``x["schema"] = ...``
    subscript store, or ``dict(schema=...)`` / any call with a ``schema``
    keyword — the stamp idioms tracer.py and store.py use.
    """
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for key in node.keys:
                if isinstance(key, ast.Constant) and key.value == "schema":
                    return True
        elif isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Store
        ):
            sl = node.slice
            if isinstance(sl, ast.Constant) and sl.value == "schema":
                return True
        elif isinstance(node, ast.Call):
            if any(kw.arg == "schema" for kw in node.keywords):
                return True
    return False


@register
class UnversionedSchemaRule(Rule):
    """Flag obs modules that write JSONL rows without a ``schema`` stamp."""

    name = "unversioned-schema"
    description = (
        "a module under obs/ writes JSONL rows but never stamps a "
        "'schema' version field; appended rows outlive the writer, so "
        "unversioned rows make every format change corrupt the corpus"
    )
    tags = ('bus', 'contract')
    rationale = (
        "Appended rows outlive the writer; unversioned rows make every format "
        "change corrupt the whole corpus."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag JSONL write sites in obs modules lacking a schema stamp."""
        if not _scoped(module):
            return
        module_aliases, func_aliases = _dumps_aliases(module.tree)
        if not module_aliases and not func_aliases:
            return
        sites = _jsonl_write_sites(module.tree, module_aliases, func_aliases)
        if not sites or _stamps_schema(module.tree):
            return
        for lineno in sites:
            yield "", lineno, (
                "JSONL row written without a 'schema' version stamp: rows "
                "in an append-only obs stream/index outlive this writer — "
                "add a module SCHEMA constant and stamp '\"schema\": "
                "SCHEMA' into every row (see obs/store.py)"
            )

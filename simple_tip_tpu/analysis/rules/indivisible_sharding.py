"""Rule ``indivisible-sharding``: sharded dims must divide by axis size.

The semantic upgrade of ``sharding-spec-mismatch``: that rule checks that a
``PartitionSpec`` names real mesh axes; this one checks that the *numbers
work out*. The tipcheck interpreter (``analysis.shapes``) tracks concrete
mesh axis sizes (``Mesh(np.asarray(jax.devices()).reshape(2, 2), ...)``
gives ``dp=2, sp=2``) alongside inferred array shapes, and verifies every
place a spec meets an array:

- ``jax.device_put(x, NamedSharding(mesh, spec))``,
- ``shard_map`` ``in_specs`` (dims are divided on entry; the quotient
  propagates through the body and is multiplied back by ``out_specs``),
- ``with_sharding_constraint`` and pjit ``in_shardings``,
- ``all_to_all(tiled=True)`` splitting a dim across the axis.

A dim 100 sharded over an 8-way axis fails at dispatch on the real slice
with an unhelpful XLA error — or silently pads, skewing throughput numbers.

Conservatism: axis sizes resolved from ``jax.device_count()``, env vars, or
any expression the interpreter cannot pin degrade to ``Dyn``, and ``Dyn``
never divides anything — no findings, no false positives on host-portable
mesh construction.
"""

from typing import Iterator, Sequence, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register


@register
class IndivisibleShardingRule(Rule):
    """Check inferred dims divide by the mesh axis sizes sharding them."""

    name = "indivisible-sharding"
    description = (
        "a PartitionSpec'd dim is not divisible by its mesh axis size "
        "for a mesh constructed in the project"
    )
    tags = ("tipcheck", "sharding", "semantic", "interprocedural")
    rationale = (
        "Axis-name checks pass while the arithmetic is wrong: a 100-long "
        "sequence over an 8-way axis dispatches nothing useful at v4-32 "
        "scale. The interpreter multiplies mesh sizes out of device-array "
        "literals and checks divisibility at every spec/array meeting "
        "point, degrading to Dyn (silent) when sizes come from runtime."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        from simple_tip_tpu.analysis.shapes import project_shapes

        for f in project_shapes(modules).findings:
            if f.kind == self.name:
                yield f.module.path, f.line, f.message

"""Rule ``prng-hygiene``: a PRNG key consumed twice produces identical draws.

JAX keys are values, not stateful generators: passing the same key to two
samplers yields the SAME randomness — statistically catastrophic and silent
(dropout masks repeat, ensemble members correlate). The fix is always a
``jax.random.split``/``fold_in`` re-derivation between uses.

Detection is a per-function-scope linear scan: a name becomes *consumed* when
passed as the key (first positional) argument to a ``jax.random.*`` sampler
or to ``split``; consuming an already-consumed name is a finding. Rebinding
the name (``rng, sub = jax.random.split(rng)``) makes it fresh again.
``fold_in(key, data)`` is exempt on both sides: deriving several streams from
one key with distinct fold data is the canonical loop idiom in this codebase
(models/train.py ``mc_dropout_votes``).

Loop bodies are scanned twice, so a consume-without-rebind inside ``for``/
``while`` is caught as the cross-iteration reuse it is; ``if`` branches are
scanned against copies of the state and merged (exclusive branches may both
consume the same key).
"""

import ast
from typing import Dict, Iterator, List, Set, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.common import callee_name, import_aliases

#: jax.random functions that do NOT consume their key argument.
_NON_CONSUMING = {
    "jax.random.PRNGKey",
    "jax.random.key",
    "jax.random.fold_in",
    "jax.random.key_data",
    "jax.random.wrap_key_data",
}

_SKIP_SUBTREES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _walk_same_scope(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested functions/classes/lambdas."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        if isinstance(sub, _SKIP_SUBTREES):
            continue
        yield sub
        stack.extend(ast.iter_child_nodes(sub))


def _assigned_names(target: ast.AST) -> List[str]:
    return [n.id for n in ast.walk(target) if isinstance(n, ast.Name)]


@register
class PrngHygieneRule(Rule):
    """Flag PRNG keys used twice without an intervening split/fold_in."""

    name = "prng-hygiene"
    description = (
        "a PRNG key passed to two jax.random consumers without an "
        "intervening split/fold_in re-derivation"
    )
    tags = ('prng', 'statistics')
    rationale = (
        "Identical draws: dropout masks repeat, ensemble members correlate — "
        "silent statistical corruption."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag PRNG keys consumed more than once without split/fold_in."""
        aliases = import_aliases(module.tree)
        scopes: List[List[ast.stmt]] = [module.tree.body]
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        reported: Set[int] = set()
        for body in scopes:
            for line, msg in self._scan(body, aliases, {}):
                if line not in reported:
                    reported.add(line)
                    yield "", line, msg

    def _scan(
        self, body: List[ast.stmt], aliases, consumed: Dict[str, int]
    ) -> Iterator[Tuple[int, str]]:
        """Walk statements in order, threading the consumed-key state."""
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested scopes are scanned independently
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from self._uses(stmt.iter, aliases, consumed)
                for name in _assigned_names(stmt.target):
                    consumed.pop(name, None)
                # Two passes: a key consumed in pass 1 and not rebound is the
                # cross-iteration reuse pass 2 reports.
                yield from self._scan(stmt.body, aliases, consumed)
                yield from self._scan(stmt.body, aliases, consumed)
                yield from self._scan(stmt.orelse, aliases, consumed)
            elif isinstance(stmt, ast.While):
                yield from self._uses(stmt.test, aliases, consumed)
                yield from self._scan(stmt.body, aliases, consumed)
                yield from self._scan(stmt.body, aliases, consumed)
                yield from self._scan(stmt.orelse, aliases, consumed)
            elif isinstance(stmt, ast.If):
                yield from self._uses(stmt.test, aliases, consumed)
                then_state = dict(consumed)
                else_state = dict(consumed)
                yield from self._scan(stmt.body, aliases, then_state)
                yield from self._scan(stmt.orelse, aliases, else_state)
                consumed.clear()
                consumed.update(then_state)
                consumed.update(else_state)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    yield from self._uses(item.context_expr, aliases, consumed)
                yield from self._scan(stmt.body, aliases, consumed)
            elif isinstance(stmt, ast.Try):
                yield from self._scan(stmt.body, aliases, consumed)
                for handler in stmt.handlers:
                    yield from self._scan(handler.body, aliases, consumed)
                yield from self._scan(stmt.orelse, aliases, consumed)
                yield from self._scan(stmt.finalbody, aliases, consumed)
            else:
                yield from self._uses(stmt, aliases, consumed)
                targets: List[ast.AST] = []
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    targets = [stmt.target]
                for target in targets:
                    for name in _assigned_names(target):
                        consumed.pop(name, None)

    def _uses(
        self, node: ast.AST, aliases, consumed: Dict[str, int]
    ) -> Iterator[Tuple[int, str]]:
        """Record every key-consuming jax.random call under ``node``."""
        calls = [node] if isinstance(node, ast.Call) else []
        calls += [n for n in _walk_same_scope(node) if isinstance(n, ast.Call)]
        # Source order: nested calls evaluate inner-first, but for reuse
        # reporting, line order reads best.
        calls.sort(key=lambda c: (c.lineno, c.col_offset))
        for call in calls:
            name = callee_name(call, aliases)
            if not name or not name.startswith("jax.random."):
                continue
            if name in _NON_CONSUMING:
                continue
            if not call.args or not isinstance(call.args[0], ast.Name):
                continue
            key = call.args[0].id
            if key in consumed:
                yield call.lineno, (
                    f"PRNG key `{key}` reused by {name}() (already consumed "
                    f"on line {consumed[key]}); derive a fresh key with "
                    "jax.random.split or fold_in"
                )
            consumed[key] = call.lineno

"""Rule ``knob-contract``: every TIP_* env read must be declared somewhere.

``hardcoded-knob`` (PR 15) polices the *write* side of the planner
contract: library code must not pin planner-owned env vars. This rule
closes the *read* side: a ``TIP_*`` name read from the environment must
be declared either in the planner's knob registry
(``plan/knobs.py`` — :func:`~simple_tip_tpu.plan.knobs.knob_for_env`) or
in :data:`NON_PLANNER_KNOBS` below, the documented allowlist of
operational (non-search) knobs. An env read satisfying neither is a knob
nobody can discover: invisible to ``plan explain``, absent from the
README knob table's source of truth, and one rename away from silently
reading nothing.

Reads are found by the dataflow layer (``analysis/dataflow.py``):
``os.environ.get``/``[]``/``setdefault`` and ``os.getenv`` with a
literal (or module-constant) name, *including interprocedural reads* —
``_env("TIP_SERVE_INFLIGHT", int, 2)`` counts as a read of
``TIP_SERVE_INFLIGHT`` at the call site because the helper's parameter
flows into its env lookup. Dynamically-built names (the ``TIP_RETRY_*``
scope family) are unresolvable and never flagged. Scripts and tests are
exempt surfaces (operators and harnesses improvise knobs legitimately).
"""

from typing import Iterator, Sequence, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.bare_print import _exempt

#: The documented non-planner knob allowlist: operational env vars that are
#: deliberately NOT in the planner's search space (they select storage
#: locations, debug surfaces and failure-policy, not performance points).
#: Grouped by owning subsystem; keep each entry next to its owner.
NON_PLANNER_KNOBS = frozenset(
    {
        # config.py / the artifact bus root
        "TIP_ASSETS",
        "TIP_DATA_DIR",
        "TIP_TMP_SWEEP_AGE_S",
        # backend/device policy (config.py, utils/devices.py)
        "TIP_ALLOW_CPU_FALLBACK",
        "TIP_COMPUTE_DTYPE",
        "TIP_JAX_CACHE",
        "TIP_PROFILE_DIR",
        "TIP_RUN_TIMEOUT_S",
        "TIP_INT8_PROFILES",
        "TIP_CAM_BACKEND",
        "TIP_CASE_STUDY_PROVIDER",
        # synthetic data scaling (data/synth.py)
        "TIP_SYNTH_HARDNESS",
        "TIP_SYNTH_SCALE",
        # engine caches (engine/sa_prep.py, engine/run_program.py,
        # ops/coverage_stats.py)
        "TIP_SA_CACHE_DIR",
        "TIP_SA_CACHE_MAX_BYTES",
        "TIP_SA_PIPELINE",
        "TIP_PROGRAM_CACHE_DIR",
        "TIP_PROGRAM_CACHE_MAX_BYTES",
        "TIP_COV_STATS_CACHE_DIR",
        # resilience plane (journal, breaker, faults, lease fleet)
        "TIP_JOURNAL",
        "TIP_JOURNAL_MAX_BYTES",
        "TIP_BREAKER_STATE",
        "TIP_BREAKER_THRESHOLD",
        "TIP_BREAKER_COOLDOWN_S",
        "TIP_BREAKER_MODE",
        "TIP_FAULT_PLAN",
        "TIP_FAULT_STATE",
        # (TIP_FLEET_HOST is write-only — the fleet stamps it into worker
        # env; nothing reads it in-package, so it is deliberately absent:
        # this list covers the read side of the contract only.)
        "TIP_FLEET_CLOCK_SKEW_S",
        "TIP_FLEET_STRAGGLER_S",
        "TIP_FLEET_STRAGGLER_SLACK",
        "TIP_FLEET_MAX_STANDBYS",
        # obs plane (obs/__init__.py, obs/store.py, obs/httpd.py)
        "TIP_OBS_DIR",
        "TIP_OBS_ROOT",
        "TIP_OBS_HTTP",
        "TIP_OBS_INDEX",
        "TIP_OBS_SAMPLE",
        "TIP_OBS_MAX_BYTES",
        "TIP_OBS_MEMPOLL_S",
        "TIP_OBS_WORKER",
        "TIP_OBS_PLATFORM",
        # alerting plane (obs/slo.py, obs/alerts.py): rule-document /
        # state-file locations, sink routing and the evaluator cadence —
        # operational surfaces, not searched plan dimensions
        "TIP_ALERT_RULES",
        "TIP_ALERT_STATE",
        "TIP_ALERT_SINKS",
        "TIP_ALERT_EVAL_S",
        # device cost observatory (obs/devicemeter.py) + the
        # healthy-window capture pilot (scripts/healthy_window.py):
        # calibration/operations knobs, not searched plan dimensions
        "TIP_DEVICE_PEAKS",
        "TIP_HEALTHZ_URL",
        "TIP_HEALTHY_POLL_S",
        "TIP_HEALTHY_DEADLINE_S",
        "TIP_HEALTHY_STREAK",
        # serving admission control (serving/knobs.py) — the badge bound
        # TIP_SERVE_MAX_BADGE is planner-owned; these are load-shed policy
        "TIP_SERVE_SHED_MODE",
        "TIP_SERVE_QUEUE_BOUND",
        "TIP_SERVE_MAX_BACKLOG_S",
        "TIP_SERVE_INFLIGHT",
        "TIP_SERVE_FLUSH_DEADLINE_MS",
        # plan plumbing (plan/plan.py): where the plan itself lives — a
        # location, not a searched knob
        "TIP_PLAN_FILE",
        "TIP_PLAN_MEM_BYTES",
    }
)


def _planner_declared(env: str) -> bool:
    """Whether the plan/knobs registry owns ``env`` (lazy import: the
    registry lives in the analyzed package and must not be a hard dep)."""
    try:
        from simple_tip_tpu.plan.knobs import knob_for_env

        return knob_for_env(env) is not None
    except Exception:  # noqa: BLE001 — analyzer availability > one rule
        return False


@register
class KnobContractRule(Rule):
    """Flag undeclared TIP_* env reads (not planner, not allowlisted)."""

    name = "knob-contract"
    description = (
        "a TIP_* env var is read but declared neither in the planner knob "
        "registry (plan/knobs.py) nor in the documented non-planner "
        "allowlist (analysis/rules/knob_contract.py): undiscoverable "
        "configuration — declare it in one of the two registries "
        "(interprocedural: helper reads count at the literal call site; "
        "scripts/tests exempt)"
    )
    tags = ('knobs', 'planner', 'interprocedural')
    rationale = (
        "An undeclared knob is invisible to plan explain, the self-tuning "
        "search, and the plan-vs-actual audit: the planner cannot reason about "
        "a dial it doesn't know exists."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        """Check every literal TIP_* read the dataflow layer resolves."""
        # Deferred import: analysis.dataflow imports analysis.graph, which
        # imports rules.common — a module-level import here would cycle
        # through rules/__init__ (same pattern as sharding_spec).
        from simple_tip_tpu.analysis.dataflow import project_flow

        pf = project_flow(modules)
        for read in pf.env_reads():
            if not read.env.startswith("TIP_"):
                continue
            if read.env in NON_PLANNER_KNOBS or _planner_declared(read.env):
                continue
            if _exempt(read.module):
                continue
            via = f" (through {read.via})" if read.via else ""
            yield read.module.path, read.line, (
                f"{read.env} is read from the environment{via} but is "
                f"neither a planner knob (plan/knobs.py) nor in the "
                f"documented non-planner allowlist "
                f"(analysis/rules/knob_contract.py): undeclared knobs are "
                f"invisible to `plan explain` and to operators — declare "
                f"it in one of the two registries"
            )

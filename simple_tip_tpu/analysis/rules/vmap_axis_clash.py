"""Rule ``vmap-axis-clash``: in_axes/out_axes inconsistent with ranks.

``jax.vmap`` axis bugs are rank bugs: an ``in_axes`` entry pointing past an
argument's rank, an ``in_axes`` tuple whose length disagrees with the call
arity, or two mapped arguments whose mapped-axis sizes differ. At runtime
these fail at trace time *if* the call site executes under test — vmapped
ensemble steps behind a flag often don't. The tipcheck interpreter
(``analysis.shapes``) knows the abstract rank and dims of every argument at
the ``vmap(...)(...)`` application, so all three inconsistencies are
checkable statically:

- ``in_axes`` tuple length != number of positional arguments,
- an integer axis outside ``[-rank, rank)`` for its argument,
- mapped-axis sizes that are both known and unequal.

Conservatism: arguments with unknown rank, non-literal ``in_axes``, and
``None`` (broadcast) entries are all skipped; ``Dyn`` sizes never clash.
"""

from typing import Iterator, Sequence, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register


@register
class VmapAxisClashRule(Rule):
    """Check vmap/pmap axis specifications against inferred ranks."""

    name = "vmap-axis-clash"
    description = (
        "vmap/pmap in_axes or out_axes inconsistent with the inferred "
        "rank or mapped-axis sizes of the arguments"
    )
    tags = ("tipcheck", "shapes", "vmap", "semantic")
    rationale = (
        "vmap axis errors surface only when the mapped call actually "
        "executes; the G-group ensemble paths are exactly the kind of "
        "conditionally-executed code where they hide. Checking in_axes "
        "against abstract ranks catches them without running anything."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        from simple_tip_tpu.analysis.shapes import project_shapes

        for f in project_shapes(modules).findings:
            if f.kind == self.name:
                yield f.module.path, f.line, f.message

"""tiplint rule catalogue — importing this package registers every rule.

Rules register themselves via the ``@register`` class decorator on import;
``core.all_rules()`` imports this package to trigger that, so adding a rule
is: create the module, decorate the class, import it here.
"""

from simple_tip_tpu.analysis.rules import (  # noqa: F401
    artifact_contract,
    bare_print,
    blocking_async,
    blocking_endpoint,
    buffer_donation,
    docstring_coverage,
    f64_on_tpu,
    hardcoded_knob,
    host_sync,
    implicit_transfer,
    jit_purity,
    naked_retry,
    prng_hygiene,
    retrace_risk,
    shape_poly,
    sharding_spec,
    transitive_purity,
    unfenced_claim,
    unversioned_schema,
    wallclock_duration,
)

"""tiplint rule catalogue — importing this package registers every rule.

Rules register themselves via the ``@register`` class decorator on import;
``core.all_rules()`` imports this package to trigger that, so adding a rule
is: create the module, decorate the class, import it here.
"""

from simple_tip_tpu.analysis.rules import (  # noqa: F401
    artifact_contract,
    bare_print,
    blocking_async,
    blocking_endpoint,
    buffer_donation,
    docstring_coverage,
    dtype_promotion,
    escaping_tracer,
    f64_on_tpu,
    hardcoded_knob,
    host_sync,
    implicit_transfer,
    indivisible_sharding,
    jit_purity,
    knob_contract,
    naked_retry,
    prng_hygiene,
    retrace_risk,
    shape_mismatch,
    shape_poly,
    sharding_spec,
    transitive_purity,
    vmap_axis_clash,
    unfenced_claim,
    unsafe_bus_write,
    unversioned_schema,
    use_after_donate,
    wallclock_duration,
)

"""Rule ``buffer-donation``: state-threading jits must donate their buffers.

A jitted step of the form ``state' = step(state, ...)`` holds BOTH the old
and new state alive across the call unless the old buffers are donated
(``donate_argnums``). For the ensemble trainers here the state is a stacked
multi-member parameter+optimizer pytree — multi-GB at paper scale — so a
missing donation doubles peak HBM and halves the trainable ensemble width.

Detection: every ``jax.jit`` application (decorator, direct call, or
``functools.partial(jax.jit, ...)``) whose wrapped callable is resolvable in
the module (a local ``def`` referenced by name, or an inline ``lambda``) and
whose parameter names include a state-carrier (``opt_state``, ``state``,
``carry``, ``opt_states``) is flagged unless the jit supplies
``donate_argnums``/``donate_argnames``. Inference-only jits (``params`` with
no optimizer state) are exempt: their parameters are reused across calls and
must NOT be donated.
"""

import ast
from typing import Iterator, List, Optional, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.common import (
    callee_name,
    dotted,
    import_aliases,
    is_partial_of,
    lambda_or_def_params,
    resolve_local_function,
)

#: Parameter names that mark a jitted callable as a state-threading step.
STATE_PARAM_NAMES = {"opt_state", "opt_states", "state", "carry"}

_JIT_NAMES = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")


def _jit_donates(keywords: List[ast.keyword]) -> bool:
    return any(
        kw.arg in ("donate_argnums", "donate_argnames") for kw in keywords
    )


@register
class BufferDonationRule(Rule):
    """Flag state-threading jit applications without donate_argnums."""

    name = "buffer-donation"
    description = (
        "jitted state-threading steps (params/opt_state style) without "
        "donate_argnums: old and new state both stay alive, doubling peak HBM"
    )
    tags = ('memory', 'perf')
    rationale = (
        "Old and new state both stay alive across an undonated step: peak HBM "
        "doubles on multi-GB stacked ensembles."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag state-threading jits that do not donate their state args."""
        aliases = import_aliases(module.tree)

        # Form 1: decorators on defs.
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                verdict = self._decorator_misses_donation(dec, aliases)
                if verdict and self._state_params(node):
                    yield "", node.lineno, self._message(node.name, node)
                    break

        # Form 2: call application — jax.jit(f), jax.jit(lambda ...),
        # partial(jax.jit, ...)(f).
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            wrapped = self._jit_application_without_donation(node, aliases)
            if wrapped is None:
                continue
            fn = self._resolve_callable(wrapped, module, aliases)
            if fn is None:
                continue
            if self._state_params(fn):
                label = getattr(fn, "name", "<lambda>")
                yield "", node.lineno, self._message(label, fn)

    def _message(self, label: str, fn) -> str:
        params = [p for p in lambda_or_def_params(fn) if p in STATE_PARAM_NAMES]
        return (
            f"jitted state-threading step `{label}` (carries {', '.join(params)}) "
            "has no donate_argnums: old and new state both stay alive across "
            "the call"
        )

    def _state_params(self, fn) -> bool:
        return bool(set(lambda_or_def_params(fn)) & STATE_PARAM_NAMES)

    def _decorator_misses_donation(self, dec: ast.AST, aliases) -> bool:
        """True when this decorator is a jit application without donation."""
        if dotted(dec, aliases) in _JIT_NAMES:
            return True  # bare @jax.jit: no kwargs at all
        if isinstance(dec, ast.Call):
            name = callee_name(dec, aliases)
            if name in _JIT_NAMES:
                return not _jit_donates(dec.keywords)
            for jit in _JIT_NAMES:
                if is_partial_of(dec, jit, aliases):
                    return not _jit_donates(dec.keywords)
        return False

    def _jit_application_without_donation(
        self, call: ast.Call, aliases
    ) -> Optional[ast.AST]:
        """The callable expression a donation-less jit wraps, else None."""
        name = callee_name(call, aliases)
        if name in _JIT_NAMES and call.args:
            if not _jit_donates(call.keywords):
                return call.args[0]
            return None
        # partial(jax.jit, ...)(f)
        if isinstance(call.func, ast.Call) and call.args:
            inner = call.func
            for jit in _JIT_NAMES:
                if is_partial_of(inner, jit, aliases):
                    if not _jit_donates(inner.keywords):
                        return call.args[0]
                    return None
        return None

    def _resolve_callable(self, expr: ast.AST, module: ModuleInfo, aliases):
        """Lambda directly, or a module-local def referenced by bare name."""
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Name):
            return resolve_local_function(expr.id, module.tree)
        return None

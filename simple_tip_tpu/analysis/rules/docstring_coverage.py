"""Rule ``docstring-coverage``: the doc-quality gate, as a lint rule.

The reference enforces docstring coverage via docstr-coverage (reference:
.docstr.yaml:1-9, Dockerfile:23-25). Previously this lived as an ad-hoc AST
walk in tests/test_docstring_coverage.py; folding it into tiplint gives one
static-analysis entry point (the test remains as a thin wrapper invoking
this rule).

Findings:

- a module without a module docstring (empty ``__init__.py`` namespace
  files are exempt);
- a package-level finding when the public class/function docstring rate
  drops below ``REQUIRED_RATE`` (0.9, same threshold as the reference's
  gate). Public defs are module- and class-level only — nested closures are
  implementation detail, not API surface. ``test_*`` functions inside test
  modules (``test_*.py``/``conftest.py``) are exempt from the rate: a test's
  name IS its spec, matching docstr-coverage's own test-exclusion default —
  the module docstring requirement still applies to test modules.
"""

import ast
from typing import Iterator, List, Sequence, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register

REQUIRED_RATE = 0.9


def public_defs(tree: ast.Module) -> Iterator[ast.AST]:
    """Module- and class-level public defs (the documented API surface)."""

    def scoped(body):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                if not node.name.startswith("_"):
                    yield node
                    if isinstance(node, ast.ClassDef):
                        yield from scoped(node.body)

    yield from scoped(tree.body)


def _is_test_module(relpath: str) -> bool:
    base = relpath.rsplit("/", 1)[-1]
    return base.startswith("test_") or base == "conftest.py"


@register
class DocstringCoverageRule(Rule):
    """Module docstrings everywhere; >= 90% documented public defs."""

    name = "docstring-coverage"
    description = (
        "every module needs a docstring and >= 90% of public "
        "classes/functions must be documented (the reference's "
        "docstr-coverage gate)"
    )
    tags = ('docs', 'hygiene')
    rationale = (
        "The reference's docstr-coverage gate, folded into the one "
        "static-analysis entry point."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Require a module docstring (empty namespace inits exempt)."""
        tree = module.tree
        if module.relpath.endswith("__init__.py") and not tree.body:
            return  # empty namespace init
        if ast.get_docstring(tree) is None:
            yield "", 1, "module has no docstring"

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        """Enforce the package-wide public docstring rate."""
        total, documented = 0, 0
        undocumented: List[Tuple[str, str, int, str]] = []
        for module in modules:
            is_test = _is_test_module(module.relpath)
            for node in public_defs(module.tree):
                if is_test and node.name.startswith("test_"):
                    continue  # the test name is the spec
                total += 1
                if ast.get_docstring(node) is not None:
                    documented += 1
                else:
                    undocumented.append(
                        (module.path, module.relpath, node.lineno, node.name)
                    )
        if not total:
            return
        rate = documented / total
        if rate < REQUIRED_RATE:
            examples = ", ".join(
                f"{rel}:{name}" for _path, rel, _line, name in undocumented[:10]
            )
            path, _rel, line, _name = undocumented[0]
            yield path, line, (
                f"public docstring coverage {rate:.0%} < "
                f"{REQUIRED_RATE:.0%} across the analyzed tree "
                f"(undocumented: {examples})"
            )

"""Rule ``wallclock-duration``: ``time.time()`` subtraction measures clock
steps, not durations.

``time.time()`` is wall clock: NTP steps, leap-second smears and manual
clock changes move it mid-measurement, so ``time.time() - t0`` in library
code can go negative or inflate a phase record by hours — exactly the
corruption the PR 4 timer fix removed from ``ops/timer.py``. The repo idiom
since then is ``time.perf_counter()`` for durations and ``time.monotonic()``
for deadlines; ``time.time()`` remains correct for *timestamps* (the obs
tracer's cross-process-alignable ``ts`` fields), which is why only the
SUBTRACTION pattern is flagged, not the call itself.

Detected: any ``a - b`` where either operand is a direct ``time.time()``
call (module alias and ``from time import time`` forms included). The
two-names form (``t1 - t0`` with both assigned from ``time.time()``
earlier) is out of scope for this syntactic rule — the sweep showed every
real offender in the package used the direct form.

Exempt (same surface logic as ``bare-print``): the ``scripts/`` and
``tests/`` trees and test modules, where wall-clock phase prints are the
interface and cross-process timestamps get subtracted legitimately.
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.bare_print import _exempt


def _time_aliases(tree: ast.Module):
    """(module aliases of ``time``, name aliases of ``time.time``)."""
    mod_aliases, fn_aliases = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mod_aliases.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    fn_aliases.add(a.asname or a.name)
    return mod_aliases, fn_aliases


def _is_wallclock_call(node, mod_aliases, fn_aliases) -> bool:
    """Whether ``node`` is a direct ``time.time()`` call."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "time":
        return isinstance(fn.value, ast.Name) and fn.value.id in mod_aliases
    return isinstance(fn, ast.Name) and fn.id in fn_aliases


@register
class WallclockDurationRule(Rule):
    """Flag ``time.time()`` subtraction (duration use) in library code."""

    name = "wallclock-duration"
    description = (
        "time.time() subtraction in library code: wall clock is not "
        "monotonic, so NTP steps corrupt the measured duration; use "
        "time.perf_counter() for durations / time.monotonic() for "
        "deadlines (scripts/tests exempt)"
    )
    tags = ('hygiene', 'perf')
    rationale = (
        "Wall clock is not monotonic: an NTP step mid-measurement corrupts the "
        "duration silently; benchmarks built on it lie."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag Sub expressions with a ``time.time()`` operand."""
        if _exempt(module):
            return
        mod_aliases, fn_aliases = _time_aliases(module.tree)
        if not (mod_aliases or fn_aliases):
            return
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)):
                continue
            if _is_wallclock_call(node.left, mod_aliases, fn_aliases) or (
                _is_wallclock_call(node.right, mod_aliases, fn_aliases)
            ):
                yield "", node.lineno, (
                    "duration measured by subtracting time.time(): wall "
                    "clock is not monotonic (NTP steps corrupt the value); "
                    "use time.perf_counter() for durations or "
                    "time.monotonic() for deadlines"
                )

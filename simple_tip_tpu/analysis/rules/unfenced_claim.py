"""Rule ``unfenced-claim``: a file-claim idiom with no expiry or fencing.

``O_CREAT|O_EXCL`` (and the hardlink variant, ``os.link``) is the repo's
atomic "exactly one winner" primitive — fault-injection claim markers, the
first claim of a work lease. Used bare in library code it is a *lock with
no way out*: the winner that crashes (this deployment's normal failure
mode — preempted hosts, killed workers) never releases the file, so every
later contender loses forever; and even with an expiry bolted on, a
claim that carries no fencing epoch lets a wedged-but-alive former holder
wake up and commit over the successor's work. That is precisely the bug
class the lease substrate (``resilience/lease.py``) exists to close:
expiry makes a dead holder's claim stealable, the monotonic epoch fences
the resurrected holder out at the commit point.

Detected: a call that passes an ``O_EXCL`` flag to ``os.open`` (any
module alias, flags combined with ``|``), or any ``os.link`` call, in a
scope whose identifiers show NO lifecycle vocabulary — nothing matching
``lease``/``fence``/``epoch``/``expire``/``expiry``/``ttl``/``deadline``.
The vocabulary test is deliberately loose: the rule's job is to make
"I wrote a bare claim file" a conscious decision, not to verify the
protocol.

Exempt: ``resilience/`` (the lease/fault substrate IS the sanctioned
implementation), plus the usual script/test surfaces — a test fixture or
a one-shot operator script may claim freely.
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.bare_print import _exempt

#: Identifier substrings that mark a claim as lifecycle-aware: any expiry
#: wording (a dead holder's claim can be reclaimed) or fencing wording
#: (a stale holder's commit can be rejected).
_LIFECYCLE_VOCAB = (
    "lease", "fence", "epoch", "expire", "expiry", "ttl", "deadline",
)


def _resilience_module(module: ModuleInfo) -> bool:
    """Whether ``module`` lives in the resilience package (the sanctioned
    home of claim/lease machinery)."""
    return "resilience" in module.relpath.split("/")[:-1]


def _scope_of(tree: ast.Module, target: ast.AST) -> ast.AST:
    """The innermost function/method enclosing ``target`` (else the module).

    The vocabulary check runs over the enclosing scope: a claim helper
    whose own code renews/expires the claim is fine even if the rest of
    the module never mentions leases.
    """
    best = tree
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for child in ast.walk(node):
            if child is target:
                best = node  # keep walking: a nested def wins over its parent
    return best


def _identifiers(scope: ast.AST):
    """Every identifier-ish string in ``scope``, lowercased."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Name):
            yield node.id.lower()
        elif isinstance(node, ast.Attribute):
            yield node.attr.lower()
        elif isinstance(node, ast.arg):
            yield node.arg.lower()
        elif isinstance(node, ast.keyword) and node.arg:
            yield node.arg.lower()
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            yield node.name.lower()


def _has_lifecycle_vocab(scope: ast.AST) -> bool:
    return any(
        any(word in ident for word in _LIFECYCLE_VOCAB)
        for ident in _identifiers(scope)
    )


def _is_excl_open(node: ast.AST) -> bool:
    """An ``os.open``-style call whose flags include ``O_EXCL``."""
    if not isinstance(node, ast.Call):
        return False
    return any(
        isinstance(n, ast.Attribute) and n.attr == "O_EXCL"
        for arg in node.args + [kw.value for kw in node.keywords]
        for n in ast.walk(arg)
    )


def _is_os_link(node: ast.AST) -> bool:
    """An ``os.link``/``link`` call (the hardlink claim idiom)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr == "link" and isinstance(f.value, ast.Name)
    return isinstance(f, ast.Name) and f.id == "link"


@register
class UnfencedClaimRule(Rule):
    """Flag O_EXCL/hardlink claim idioms lacking expiry/fencing vocabulary."""

    name = "unfenced-claim"
    description = (
        "O_EXCL/os.link claim idiom with no expiry or fencing epoch in "
        "library code: a crashed winner never releases the claim and a "
        "wedged stale holder can still commit; use "
        "resilience.lease.LeaseManager (TTL + fencing epoch) or handle "
        "expiry/fencing in the claiming scope (resilience/, scripts/, "
        "tests exempt)"
    )
    tags = ('resilience', 'concurrency')
    rationale = (
        "A crashed winner never releases an unexpiring claim, and a wedged "
        "stale holder can still commit; leases need TTL plus a fencing epoch."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag lifecycle-blind claim calls outside the exempt surfaces."""
        if _exempt(module) or _resilience_module(module):
            return
        for node in ast.walk(module.tree):
            excl = _is_excl_open(node)
            if not excl and not _is_os_link(node):
                continue
            scope = _scope_of(module.tree, node)
            if _has_lifecycle_vocab(scope):
                continue
            idiom = "os.open(..., O_EXCL)" if excl else "os.link"
            yield "", node.lineno, (
                f"{idiom} claim with no expiry/fencing in scope: a holder "
                "that dies never releases it (contenders lose forever) and "
                "a wedged holder can commit stale work; claim through "
                "resilience.lease.LeaseManager, or give the claim a TTL "
                "and a fencing epoch"
            )

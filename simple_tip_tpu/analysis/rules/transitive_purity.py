"""Rule ``transitive-jit-purity``: impurity reached THROUGH the call graph.

The local ``jit-purity`` rule sees one module at a time, so the classic
failure slips through: a jitted function in ``a.py`` calls a helper that
lives in ``b.py``, and the helper prints, mutates a global, or calls numpy.
The helper's own module gives no hint it is device code — nothing flags it
locally — yet under trace its side effects run once at trace time and its
numpy calls break tracing. Whole-program reasoning is exactly what made
full-program TPU compilation workable in the Julia→TPU work (PAPERS.md);
this rule is the lint-time analogue.

Mechanics (on top of ``analysis.graph.ProjectGraph``):

- every *traced entry* — a function locally jit-reachable in its own
  module, or one traced from ANOTHER module via a jit/shard_map/pallas_call
  boundary the graph resolved — is a root;
- the rule walks resolvable call edges (bare names, imported names,
  ``mod.fn`` chains, ``functools.partial`` wrappers) breadth-first from
  each root, bounded in depth, skipping callees that are locally
  jit-reachable in their own module (the per-file rule already covers
  them — no duplicate findings);
- an impure construct (the ``jit_purity.iter_impurities`` checks) found in
  a callee is flagged **at the call site inside traced code**, with the
  full call chain printed: the line a reviewer must change is where traced
  code commits to the impure helper, not the helper itself (which may be
  perfectly fine as host code).

For a function traced only cross-module, its OWN body impurities are also
reported — at the boundary that traces it (e.g. the ``shard_map`` call
site), since no local rule will ever look inside it.
"""

from typing import Iterator, List, Sequence, Set, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.common import import_aliases
from simple_tip_tpu.analysis.rules.jit_purity import iter_impurities

MAX_DEPTH = 6


def _impurities(fi) -> List[Tuple[int, str]]:
    """Impure (line, message) pairs in one FunctionInfo's body."""
    aliases = import_aliases(fi.module.tree)
    return list(iter_impurities(fi.node, aliases))


@register
class TransitiveJitPurityRule(Rule):
    """Propagate the jit-purity checks through the project call graph."""

    name = "transitive-jit-purity"
    description = (
        "impure helpers (print/numpy/global mutation/concretization) "
        "reached from traced code through cross-module call chains, "
        "flagged at the call site with the chain printed"
    )
    tags = ('traced', 'interprocedural')
    rationale = (
        "The helper's own module looks like innocent host code — only "
        "whole-program reasoning sees it execute under trace."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        """Walk the call graph from every traced entry, flagging impurity."""
        # Deferred import: analysis.graph imports rules.common, so importing
        # it at module level would cycle through rules/__init__.
        from simple_tip_tpu.analysis.graph import project_graph

        graph = project_graph(modules)
        reported: Set[Tuple[str, int, str, int]] = set()

        for entry, boundary in graph.traced_entries():
            # A cross-module-only entry is never scanned by the local rule:
            # surface its own impurities at the boundary that traces it.
            if boundary is not None:
                for line, msg in _impurities(entry):
                    key = (boundary.module.path, boundary.line, entry.dotted, line)
                    if key in reported:
                        continue
                    reported.add(key)
                    yield boundary.module.path, boundary.line, (
                        f"{boundary.transform}({entry.qualname}) traces "
                        f"{entry.dotted} ({entry.module.relpath}:{line}), "
                        f"which is impure there: {msg}"
                    )
            # Findings anchor at the FIRST call site inside the traced
            # entry — the line where traced code commits to the (eventual)
            # impure helper — no matter how deep the chain goes from there.
            for call, callee in graph.calls_from(entry.module, entry.node):
                yield from self._walk(
                    graph, callee, [entry, callee],
                    entry.module, call.lineno, reported,
                )

    def _walk(
        self,
        graph,
        fi,
        chain: List,
        anchor_module: ModuleInfo,
        anchor_line: int,
        reported: Set[Tuple[str, int, str, int]],
    ) -> Iterator[Tuple[str, int, str]]:
        if len(chain) > MAX_DEPTH or fi in chain[:-1]:
            return  # depth bound / recursion cycle
        if fi.node in graph.jit_reachable(fi.module):
            return  # the local jit-purity rule owns this function
        for line, msg in _impurities(fi):
            key = (anchor_module.path, anchor_line, fi.dotted, line)
            if key in reported:
                continue
            reported.add(key)
            path = " -> ".join(f.qualname for f in chain)
            yield anchor_module.path, anchor_line, (
                f"traced call chain {path} reaches impure code in "
                f"{fi.dotted} ({fi.module.relpath}:{line}): {msg}"
            )
        for _call, callee in graph.calls_from(fi.module, fi.node):
            yield from self._walk(
                graph, callee, chain + [callee],
                anchor_module, anchor_line, reported,
            )

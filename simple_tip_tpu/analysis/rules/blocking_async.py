"""Rule ``blocking-in-async``: blocking calls inside ``async def`` bodies.

The serving engine runs one asyncio scheduler loop for EVERY tenant's
requests: a single blocking call inside a coroutine stalls the whole
request plane for its duration — batch assembly stops, flush deadlines
blow, and the p99 the SLO gate watches spikes with no counter explaining
why. The repo idiom is to keep blocking work in named sync methods and
run them via ``loop.run_in_executor`` (serving/engine.py's
``_run_badge_sync`` is the template).

Flagged lexically inside an ``async def`` body (nested sync ``def``s and
lambdas are skipped — their bodies execute elsewhere, usually exactly in
that executor thread):

- ``time.sleep(...)`` (module-alias and ``from time import sleep`` forms)
  — use ``await asyncio.sleep``;
- blocking ``<future>.result(...)`` — await the future (or wrap it with
  ``asyncio.wrap_future``);
- sync file IO via builtin ``open(...)`` — move it to a sync helper run
  off-loop.

Exempt (same surface logic as ``bare-print``): the ``scripts/`` and
``tests/`` trees, entry-point modules, and test modules — a smoke script
blocking its private loop harms nobody.
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.bare_print import _exempt
from simple_tip_tpu.analysis.rules.naked_retry import _is_time_call, _time_aliases

_NESTED_SCOPES = (ast.FunctionDef, ast.Lambda)


def _async_body_nodes(fn: ast.AsyncFunctionDef):
    """Nodes lexically in ``fn``'s body, not descending into nested sync
    scopes (their code runs elsewhere) or nested async defs (they are
    visited as their own roots by the caller's walk)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, _NESTED_SCOPES + (ast.AsyncFunctionDef,)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@register
class BlockingInAsyncRule(Rule):
    """Flag time.sleep / blocking .result() / open() in async bodies."""

    name = "blocking-in-async"
    description = (
        "blocking call (time.sleep / Future.result() / open()) inside an "
        "async def stalls the whole event loop; await the async form or "
        "run it via loop.run_in_executor (scripts/tests exempt)"
    )
    tags = ('async', 'perf')
    rationale = (
        "One blocking call in the serving engine's event loop stalls every "
        "tenant's request plane: batch assembly stops, flush deadlines blow, "
        "p99 spikes with no counter saying why."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag blocking calls lexically inside async function bodies."""
        if _exempt(module):
            return
        mod_aliases, fn_aliases = _time_aliases(module.tree)
        for fn in ast.walk(module.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _async_body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                if _is_time_call(node, "sleep", mod_aliases, fn_aliases):
                    yield "", node.lineno, (
                        f"time.sleep() inside async def {fn.name!r} blocks "
                        "the event loop (and every other tenant's badges); "
                        "use `await asyncio.sleep(...)`"
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "result"
                ):
                    yield "", node.lineno, (
                        f".result() inside async def {fn.name!r} blocks the "
                        "event loop waiting on a future; await it (or "
                        "asyncio.wrap_future it) instead"
                    )
                elif (
                    isinstance(node.func, ast.Name) and node.func.id == "open"
                ):
                    yield "", node.lineno, (
                        f"sync file IO (open()) inside async def {fn.name!r} "
                        "blocks the event loop; do the IO in a sync helper "
                        "via loop.run_in_executor"
                    )

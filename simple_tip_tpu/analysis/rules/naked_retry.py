"""Rule ``naked-retry``: a ``time.sleep`` poll/retry loop with no exit
budget spins forever on a wedged dependency.

The documented failure mode of this deployment is a tunnel that WEDGES —
calls hang rather than error — so any ``while ...: time.sleep(...)`` loop
in library code whose condition can simply never become true (a probe that
never answers, a file that never appears) turns into the hang the
watchdog/scheduler machinery exists to prevent. The repo idiom is
``resilience/retry.py``: bounded attempts, exponential backoff and a
``time.monotonic`` deadline. This rule flags the loops that predate (or
bypass) it.

Detected: a ``while`` loop in library code that calls ``time.sleep``
(module-alias and ``from time import sleep`` forms) and shows NEITHER of
the two escape hatches:

- a **deadline**: a ``time.monotonic()``/``time.perf_counter()`` call
  anywhere in the loop, or a clock read (including ``time.time()``) in the
  loop *condition* — both shapes bound the loop in wall time;
- a **backoff**: the slept duration is a variable that the loop body
  grows multiplicatively (``delay *= 2`` / ``delay = min(delay * 2, cap)``)
  — geometric growth bounds the *rate*, which is the other accepted
  contract (and what ``RetryPolicy.delays()`` provides ready-made).

``for``-loop sleeps are out of scope: iteration over a finite sequence
(e.g. ``RetryPolicy.delays()``) is already bounded.

Exempt (same surface logic as ``bare-print``): the ``scripts/`` and
``tests/`` trees and test modules — an operator-facing watch script that
polls forever IS its contract (scripts/tunnel_watch.sh's python siblings).
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.bare_print import _exempt

#: time-module attributes whose call marks a wall-clock budget.
_CLOCK_FNS = ("monotonic", "perf_counter", "time")
#: Of those, the ones accepted ANYWHERE in the loop (not just the test):
#: a monotonic read in the body is almost always a deadline check; a bare
#: time.time() in the body could be a timestamp, so it only counts when it
#: appears in the loop condition itself.
_BODY_CLOCK_FNS = ("monotonic", "perf_counter")


def _time_aliases(tree: ast.Module):
    """(module aliases of ``time``, {fn-name -> set of import aliases})."""
    mod_aliases, fn_aliases = set(), {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mod_aliases.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                fn_aliases.setdefault(a.name, set()).add(a.asname or a.name)
    return mod_aliases, fn_aliases


def _is_time_call(node, fn: str, mod_aliases, fn_aliases) -> bool:
    """Whether ``node`` is a direct call of ``time.<fn>`` (any alias form)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == fn:
        return isinstance(f.value, ast.Name) and f.value.id in mod_aliases
    return isinstance(f, ast.Name) and f.id in fn_aliases.get(fn, set())


def _multiplied_names(body) -> set:
    """Names the loop body grows multiplicatively (the backoff shape)."""
    grown = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Mult):
                if isinstance(node.target, ast.Name):
                    grown.add(node.target.id)
            elif isinstance(node, ast.Assign):
                has_mult = any(
                    isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mult)
                    for n in ast.walk(node.value)
                )
                if has_mult:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            grown.add(tgt.id)
    return grown


@register
class NakedRetryRule(Rule):
    """Flag deadline-less, backoff-less ``time.sleep`` while-loops."""

    name = "naked-retry"
    description = (
        "time.sleep retry/poll loop without a deadline or backoff in "
        "library code: on this deployment dependencies WEDGE rather than "
        "error, so an unbounded poll loop becomes a hang; bound it with a "
        "time.monotonic deadline or route it through resilience/retry.py "
        "(scripts/tests exempt)"
    )
    tags = ('resilience',)
    rationale = (
        "On this deployment dependencies wedge rather than error, so an "
        "unbounded poll loop is a hang; bound it or route it through "
        "resilience/retry.py."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag while-loops sleeping with neither deadline nor backoff."""
        if _exempt(module):
            return
        mod_aliases, fn_aliases = _time_aliases(module.tree)
        if not (mod_aliases or "sleep" in fn_aliases):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.While):
                continue
            sleeps = [
                n
                for n in ast.walk(node)
                if _is_time_call(n, "sleep", mod_aliases, fn_aliases)
            ]
            if not sleeps:
                continue
            # Escape hatch 1: a wall-time budget.
            has_deadline = any(
                _is_time_call(n, fn, mod_aliases, fn_aliases)
                for n in ast.walk(node)
                for fn in _BODY_CLOCK_FNS
            ) or any(
                _is_time_call(n, fn, mod_aliases, fn_aliases)
                for n in ast.walk(node.test)
                for fn in _CLOCK_FNS
            )
            if has_deadline:
                continue
            # Escape hatch 2: geometric backoff of the slept duration.
            grown = _multiplied_names(node.body + node.orelse)
            for sleep_call in sleeps:
                arg = sleep_call.args[0] if sleep_call.args else None
                if isinstance(arg, ast.Name) and arg.id in grown:
                    continue
                yield "", sleep_call.lineno, (
                    "time.sleep in a while-loop with no time.monotonic "
                    "deadline and no backoff: a dependency that wedges "
                    "(never satisfies the condition) hangs this loop "
                    "forever; add a monotonic deadline or use "
                    "resilience.retry.RetryPolicy"
                )

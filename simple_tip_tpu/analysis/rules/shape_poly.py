"""Rule ``shape-polymorphism``: concrete-shape escapes inside traced code.

Inside a traced function, ``x.shape`` components are Python ints *today* —
and every place one escapes into Python-level control flow or a baked
literal is a landmine for the shape-polymorphic regimes this framework is
growing into: ``jax.export`` with symbolic dimensions, dynamic batch sizes,
re-tracing per shape. The TF→JAX migration literature (PAPERS.md) ranks
concrete-shape assumptions alongside sharding drift as the dominant
migration defect classes; a reproduction package migrated from TF 2.6.1
needs a gate for exactly these.

Flags, inside jit-reachable functions (``common.jit_reachable_functions`` —
jit/vmap/scan/shard_map/pallas kernels):

- Python ``if``/``while`` tests on a traced dimension (``x.shape``/
  ``x.size`` or a cast of one): under a symbolic dimension the comparison
  raises; under re-tracing it silently bakes one branch per shape. Use
  ``jax.lax.cond`` or hoist the decision out of the traced function.
- Python ``for`` loops bounded by a traced dimension (``range(x.shape[0])``
  and friends): the loop unrolls at trace time into shape-specific programs
  (compile-time blowup) and breaks under symbolic dims. Use
  ``jax.lax.fori_loop``/``scan``.
- ``len(<arg>)`` on a traced function argument: concretizes the leading
  dimension as a Python int. ``x.shape[0]`` survives ``jax.export``
  symbolic dimensions; ``len`` never does.
- fully-literal ``reshape`` target shapes (every dim a constant, at least
  one > 1): the array's true factorization is baked in, so the first
  different channel count / batch size silently mis-folds or errors at
  trace time. Derive dims from ``x.shape`` (or ``-1``) instead.

All checks are per-function and purely syntactic; whether the function is
traced AT ALL may be decided in another module (shard_map/pallas_call
boundaries) — that reachability extension lives in ``common`` and the
project graph.
"""

import ast
from typing import Iterator, Optional, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.common import (
    callee_name,
    function_body_nodes,
    import_aliases,
    jit_reachable_functions,
    lambda_or_def_params,
)

_DIM_ATTRS = ("shape", "size")


def _mentions_traced_dim(node: ast.AST) -> Optional[str]:
    """The dotted-ish source of a traced-dimension reference in ``node``
    (e.g. ``x.shape``), or None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _DIM_ATTRS:
            base = sub.value
            label = base.id if isinstance(base, ast.Name) else "..."
            return f"{label}.{sub.attr}"
    return None


def _literal_reshape_dims(call: ast.Call, aliases) -> Optional[Tuple[int, ...]]:
    """The fully-literal target shape of a reshape call, or None.

    Matches ``x.reshape(a, b, ...)`` / ``x.reshape((a, b))`` and
    ``jnp.reshape(x, (a, b))`` where EVERY dim is an int constant. Mixed
    shapes (some dims derived from ``x.shape``) and ``-1`` wildcards are
    fine — only a completely baked shape is a finding.
    """
    name = callee_name(call, aliases)
    if name in ("jax.numpy.reshape", "numpy.reshape"):
        dim_args = call.args[1:]
    elif isinstance(call.func, ast.Attribute) and call.func.attr == "reshape":
        dim_args = list(call.args)
    else:
        return None
    if not dim_args:
        return None
    if len(dim_args) == 1 and isinstance(dim_args[0], (ast.Tuple, ast.List)):
        dim_args = list(dim_args[0].elts)
    dims = []
    for arg in dim_args:
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, int)):
            return None
        dims.append(arg.value)
    if not any(d > 1 for d in dims):
        return None  # reshape(-1), reshape(1, -1): layout-only, shape-safe
    return tuple(dims)


@register
class ShapePolymorphismRule(Rule):
    """Flag concrete-shape escapes inside traced functions."""

    name = "shape-polymorphism"
    description = (
        "Python control flow on traced dimensions, len() on traced "
        "arguments and fully-literal reshape shapes inside traced "
        "functions — the concrete-shape assumptions that break under "
        "jax.export / dynamic batch sizes"
    )
    tags = ('shapes', 'traced')
    rationale = (
        "Concrete-shape escapes break under jax.export symbolic dims and "
        "dynamic batch sizes, and unroll or re-trace per shape."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag concrete-shape escapes in the module's traced functions."""
        aliases = import_aliases(module.tree)
        reachable = jit_reachable_functions(module.tree, aliases)
        seen = set()
        for fn in reachable:
            params = set(lambda_or_def_params(fn))
            for node in function_body_nodes(fn):
                for line, msg in self._check_node(node, params, aliases):
                    if line not in seen:
                        seen.add(line)
                        yield "", line, msg

    def _check_node(self, node, params, aliases):
        if isinstance(node, (ast.If, ast.While)):
            hit = _mentions_traced_dim(node.test)
            if hit is not None:
                kind = "if" if isinstance(node, ast.If) else "while"
                yield node.lineno, (
                    f"Python `{kind}` on a traced dimension ({hit}) inside "
                    "a traced function: bakes one branch per shape and "
                    "breaks under jax.export symbolic dims; use "
                    "jax.lax.cond or hoist the decision out of the trace"
                )
        elif isinstance(node, ast.For):
            if isinstance(node.iter, ast.Call) and callee_name(
                node.iter, aliases
            ) in ("range", "builtins.range"):
                hit = _mentions_traced_dim(node.iter)
                if hit is not None:
                    yield node.lineno, (
                        f"Python `for` bounded by a traced dimension ({hit}) "
                        "inside a traced function: unrolls at trace time "
                        "per shape; use jax.lax.fori_loop or scan"
                    )
        elif isinstance(node, ast.Call):
            name = callee_name(node, aliases)
            if (
                name == "len"
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in params
            ):
                yield node.lineno, (
                    f"len({node.args[0].id}) on a traced function argument "
                    "concretizes its leading dimension; use "
                    f"{node.args[0].id}.shape[0], which survives jax.export "
                    "symbolic dims"
                )
            else:
                dims = _literal_reshape_dims(node, aliases)
                if dims is not None:
                    shape = ", ".join(str(d) for d in dims)
                    yield node.lineno, (
                        f"reshape({shape}) bakes a fully-literal shape into "
                        "traced code: the first different channel/batch size "
                        "mis-folds silently; derive dims from the operand's "
                        ".shape (or use -1)"
                    )

"""Rule ``use-after-donate``: a donated buffer must never be read again.

``donate_argnums`` hands the argument's buffer to XLA: after the dispatch
the caller-side array is *deleted* on TPU — touching it raises (at best)
or aliases freshly-written memory (at worst, and only on device, so the
CPU tier-1 suite never sees it). The ``buffer-donation`` rule pushes code
*toward* donation; this rule catches the resulting footgun: a value
passed at a donated position that some execution path reads again before
rebinding it.

Detection is flow-sensitive (``analysis/dataflow.py``): every donating
jit application is resolved to its literal donated positions —

- ``@partial(jax.jit, donate_argnums=...)`` decorated defs,
- ``step = jax.jit(f, donate_argnums=...)`` / ``partial(jax.jit,
  donate_argnums=...)(f)`` local bindings,
- ``self._step = jax.jit(...)`` class-attribute bindings called through
  ``self._step(...)``,
- factory functions whose return statement *is* a donating application
  (the ``make_jitted_epoch`` pattern in models/train.py), resolved through
  the project graph so cross-module factories count —

then every call of a donating callable seeds the donated argument names
as poison in the enclosing function's CFG, killed by redefinition, and
any reaching read is a finding. The loop back edge matters: an un-rebound
state threaded around a ``for`` is read again on iteration two. Dynamic
``donate_argnums`` expressions (``_donate(1)``) are unknown, never
flagged. The finding message renders the chain: jit bind site → dispatch
→ violating read.
"""

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.buffer_donation import _JIT_NAMES
from simple_tip_tpu.analysis.rules.common import (
    callee_name,
    is_partial_of,
)


def _donate_positions(keywords: List[ast.keyword]) -> Optional[Tuple[int, ...]]:
    """Literal donated positions, or None (absent / dynamic = unknown)."""
    for kw in keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in v.elts
            ):
                return tuple(e.value for e in v.elts)
            return None
    return None


def _donating_application(
    call: ast.Call, aliases: Dict[str, str]
) -> Optional[Tuple[int, ...]]:
    """Donated positions when ``call`` applies jit with literal donation:
    ``jax.jit(f, donate_argnums=...)`` or ``partial(jax.jit, ...)(f)``."""
    name = callee_name(call, aliases)
    if name in _JIT_NAMES and call.args:
        return _donate_positions(call.keywords)
    if isinstance(call.func, ast.Call) and call.args:
        for jit in _JIT_NAMES:
            if is_partial_of(call.func, jit, aliases):
                return _donate_positions(call.func.keywords)
    return None


def _decorator_positions(
    fn: ast.AST, aliases: Dict[str, str]
) -> Optional[Tuple[int, ...]]:
    """Donated positions a jit decorator declares on ``fn``, or None."""
    for dec in getattr(fn, "decorator_list", []):
        if not isinstance(dec, ast.Call):
            continue
        if callee_name(dec, aliases) in _JIT_NAMES:
            pos = _donate_positions(dec.keywords)
            if pos:
                return pos
        for jit in _JIT_NAMES:
            if is_partial_of(dec, jit, aliases):
                pos = _donate_positions(dec.keywords)
                if pos:
                    return pos
    return None


#: A donor: donated positions + where the jit binding happened (for the
#: chain rendering in the finding message).
Donor = Tuple[Tuple[int, ...], int]


@register
class UseAfterDonateRule(Rule):
    """Flag reads of a value after it was passed at a donated position."""

    name = "use-after-donate"
    description = (
        "a value passed at a donate_argnums position of a jit'd callable "
        "is read again on some path after the dispatch: donation deletes "
        "the buffer on TPU, so the read raises or aliases garbage — "
        "rebind the result or pass a copy (flow-sensitive; dynamic "
        "donate_argnums are never flagged)"
    )
    tags = ('memory', 'correctness', 'dataflow')
    rationale = (
        "Donation hands the buffer to XLA for reuse; a post-call read returns "
        "whatever the next dispatch scribbled there — garbage gradients with no "
        "exception on TPU."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        """Per module: collect donating callables, then poison-check every
        dispatch of one inside every function body."""
        # Deferred import: analysis.dataflow imports analysis.graph, which
        # imports rules.common — a module-level import here would cycle
        # through rules/__init__ (same pattern as sharding_spec).
        from simple_tip_tpu.analysis.dataflow import project_flow

        pf = project_flow(modules)
        factories = self._factories(modules, pf)
        for module in modules:
            donors = self._donors(module, pf, factories)
            if not donors:
                continue
            yield from self._check_dispatches(module, pf, donors)

    # -- donor collection --------------------------------------------------

    def _factories(self, modules, pf) -> Dict[int, Tuple[int, ...]]:
        """id(def node) -> donated positions, for functions whose return
        value is a donating jit application (jit factories)."""
        from simple_tip_tpu.analysis.dataflow import (
            iter_function_nodes,
            scope_walk,
        )

        out: Dict[int, Tuple[int, ...]] = {}
        for module in modules:
            aliases = pf.aliases(module)
            for fn in iter_function_nodes(module.tree):
                if isinstance(fn, ast.Lambda):
                    continue
                for stmt in scope_walk(fn):
                    if not (
                        isinstance(stmt, ast.Return)
                        and isinstance(stmt.value, ast.Call)
                    ):
                        continue
                    pos = _donating_application(stmt.value, aliases)
                    if pos:
                        out[id(fn)] = pos
                        break
        return out

    def _donors(self, module, pf, factories) -> Dict[str, Donor]:
        """callable name (``step`` / ``self._step``) -> donor record."""
        from simple_tip_tpu.analysis.dataflow import iter_function_nodes

        aliases = pf.aliases(module)
        donors: Dict[str, Donor] = {}
        for fn in iter_function_nodes(module.tree):
            if isinstance(fn, ast.Lambda):
                continue
            pos = _decorator_positions(fn, aliases)
            if pos:
                donors[fn.name] = (pos, fn.lineno)
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            target = node.targets[0]
            name = None
            if isinstance(target, ast.Name):
                name = target.id
            elif (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                name = f"self.{target.attr}"
            if name is None or not isinstance(node.value, ast.Call):
                continue
            pos = _donating_application(node.value, aliases)
            if pos is None:
                # a call to a jit factory also binds a donating callable
                callee = callee_name(node.value, aliases)
                fi = pf.graph.resolve_function(module, callee) if callee else None
                if fi is not None:
                    pos = factories.get(id(fi.node))
            if pos:
                donors.setdefault(name, (pos, node.lineno))
        return donors

    # -- dispatch poison check ---------------------------------------------

    def _check_dispatches(self, module, pf, donors):
        from simple_tip_tpu.analysis.dataflow import (
            iter_function_nodes,
            scope_walk,
        )

        aliases = pf.aliases(module)
        for fn in iter_function_nodes(module.tree):
            if isinstance(fn, ast.Lambda):
                continue
            flow = None
            for node in scope_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = callee_name(node, aliases)
                donor = donors.get(name) if name else None
                if donor is None:
                    continue
                positions, bind_line = donor
                if flow is None:
                    flow = pf.flow(fn)
                stmt_idx = flow.statement_of(node)
                if stmt_idx is None:
                    continue  # dispatch inside a nested scope's own flow
                for pos in positions:
                    if pos >= len(node.args):
                        continue
                    arg = node.args[pos]
                    if not isinstance(arg, ast.Name):
                        continue
                    if arg.id in flow.writes(stmt_idx):
                        continue  # `x, y = step(x, y)` rebinds: poison dies
                    for use in flow.reaching_uses(stmt_idx, arg.id):
                        yield module.path, use.lineno, (
                            f"`{arg.id}` is read here after being donated: "
                            f"jit bound with donate_argnums at line "
                            f"{bind_line} -> `{name}(...)` dispatch at line "
                            f"{node.lineno} donates argument {pos} "
                            f"(`{arg.id}`) -> read at line {use.lineno} "
                            f"touches a deleted buffer on TPU; rebind the "
                            f"result over `{arg.id}` or pass a copy"
                        )

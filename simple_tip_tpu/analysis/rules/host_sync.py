"""Rule ``host-sync``: implicit device→host transfers in hot-path modules.

On TPU, every ``np.asarray(jnp_value)`` / ``np.array(jnp_value)`` blocks the
Python thread until the device catches up and then DMAs the buffer to host —
fine at a phase boundary, lethal inside a per-badge or per-batch loop. The
hot-path modules (``ops/``, ``parallel/``, ``engine/``) are exactly where
such syncs hide, so the rule is scoped to them; plotters and data prep are
host code by design.

Flags, in hot-path modules only:

- ``np.asarray(...)``/``np.array(...)`` whose argument expression itself
  builds a device value (contains a ``jax.numpy``/``jnp`` reference): the
  device result is synced to host the moment it is produced. Hoist the
  conversion to the phase boundary (and suppress with a justification when
  the sync IS the phase boundary).
- ``if``/``while`` tests containing a ``jax.numpy`` call inside a traced
  function: branching on a traced value concretizes it (TracerBoolError at
  best, a silent sync under ``io_callback``-style wrappers at worst).
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.common import (
    callee_name,
    contains_jnp,
    function_body_nodes,
    import_aliases,
    jit_reachable_functions,
)

#: Module prefixes (relative to the analyzed root) treated as hot paths.
HOT_PATH_PREFIXES = ("ops/", "parallel/", "engine/")

_CONVERTERS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}


@register
class HostSyncRule(Rule):
    """Flag implicit device→host syncs in ops/, parallel/ and engine/."""

    name = "host-sync"
    description = (
        "np.asarray/np.array on freshly-built jax values and branches on "
        "traced values in hot-path modules (ops/, parallel/, engine/)"
    )
    tags = ('perf', 'transfer')
    rationale = (
        "Each implicit device->host transfer blocks until the device queue "
        "drains; lethal inside per-badge loops."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag implicit syncs and traced-value branches in hot paths."""
        if not module.relpath.startswith(HOT_PATH_PREFIXES):
            return
        aliases = import_aliases(module.tree)

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = callee_name(node, aliases)
            if name in _CONVERTERS and node.args:
                hit = contains_jnp(node.args[0], aliases)
                if hit is not None:
                    yield "", node.lineno, (
                        f"{name.replace('numpy', 'np')}() over a fresh device "
                        f"value ({hit[1]} at line {hit[0]}): implicit "
                        "device->host sync; hoist the transfer to the phase "
                        "boundary"
                    )

        reachable = jit_reachable_functions(module.tree, aliases)
        seen = set()
        for fn in reachable:
            for node in function_body_nodes(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                if node.lineno in seen:
                    continue
                for sub in ast.walk(node.test):
                    if isinstance(sub, ast.Call):
                        sub_name = callee_name(sub, aliases)
                        if sub_name and sub_name.startswith("jax.numpy."):
                            seen.add(node.lineno)
                            yield "", node.lineno, (
                                f"branching on a traced value ({sub_name}) "
                                "inside a traced function forces "
                                "concretization; use jax.lax.cond/jnp.where"
                            )
                            break

"""Rule ``implicit-device-transfer``: name-dataflow device→host syncs in
engine scoring paths.

The ``host-sync`` rule flags ``np.asarray(<expr containing jnp>)`` — the
conversion and the device computation in ONE expression. The pattern that
actually crept into engine scoring code is the two-step form::

    scores = _score_fn(badge)          # _score_fn = jax.jit(...)
    out.append(np.asarray(scores))     # per-badge device->host sync

The argument is a bare name, so the expression-local check never sees the
device value. This rule tracks that one level of dataflow per scope: a name
assigned from a jnp-building expression, from a call to a locally-jitted
function, or from another tainted name is tainted; passing a tainted name to
``np.asarray``/``np.array``/``np.ascontiguousarray`` flags. Re-binding a
name to a host expression untaints it.

Scoped to ``engine/`` only (the prio scoring paths this PR made
device-resident): ops/ converts at kernel boundaries by design and carries
audited host-sync suppressions, and attribute calls
(``self._fused_fn(...)``) are deliberately NOT tracked — the coverage
badge-pull is an intentional, documented accumulation point.
"""

import ast
from typing import Dict, Iterator, Set, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.common import (
    _transform_target,
    callee_name,
    contains_jnp,
    import_aliases,
    jit_reachable_functions,
)

_CONVERTERS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}

_SCOPE_PREFIX = "engine/"


@register
class ImplicitTransferRule(Rule):
    """Flag np.asarray/np.array on names holding device values in engine/."""

    name = "implicit-device-transfer"
    description = (
        "np.asarray/np.array on a NAME assigned from a jnp expression or a "
        "locally-jitted call in engine/ scoring paths (the dataflow "
        "complement of host-sync's expression-local check)"
    )
    tags = ('perf', 'transfer', 'dataflow')
    rationale = (
        "The name-assignment variant of host-sync: the jnp value crosses a "
        "local binding before np.asarray, so only flow-sensitive tracking sees "
        "the transfer."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Track one level of device-value dataflow per scope and flag
        host conversions of tainted names."""
        if not module.relpath.startswith(_SCOPE_PREFIX):
            return
        aliases = import_aliases(module.tree)

        jitted: Set[str] = set()
        for fn in jit_reachable_functions(module.tree, aliases):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                jitted.add(fn.name)
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _transform_target(node.value.func, aliases)
            ):
                jitted.add(node.targets[0].id)

        scopes = [module.tree.body] + [
            fn.body
            for fn in ast.walk(module.tree)
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for body in scopes:
            yield from self._scan(body, aliases, jitted, set())

    def _is_device_expr(
        self,
        expr: ast.AST,
        aliases: Dict[str, str],
        jitted: Set[str],
        tainted: Set[str],
    ) -> bool:
        """Does this RHS produce a device value (one dataflow level)?"""
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Call):
            name = callee_name(expr, aliases)
            if name in jitted:
                return True
        return contains_jnp(expr, aliases) is not None

    def _flag_calls(
        self, node: ast.AST, aliases: Dict[str, str], tainted: Set[str]
    ) -> Iterator[Tuple[str, int, str]]:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = callee_name(sub, aliases)
            if (
                name in _CONVERTERS
                and sub.args
                and isinstance(sub.args[0], ast.Name)
                and sub.args[0].id in tainted
            ):
                yield "", sub.lineno, (
                    f"{name.replace('numpy', 'np')}({sub.args[0].id}) syncs a "
                    "device value produced earlier in this scope: implicit "
                    "device->host transfer; keep scoring device-resident and "
                    "transfer once at the phase boundary"
                )

    def _scan(
        self,
        stmts,
        aliases: Dict[str, str],
        jitted: Set[str],
        tainted: Set[str],
    ) -> Iterator[Tuple[str, int, str]]:
        """Source-order walk of one scope's statements, skipping nested
        function/class bodies (they scan as their own scopes)."""
        for stmt in stmts:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
            ):
                yield from self._flag_calls(stmt.value, aliases, tainted)
                if self._is_device_expr(stmt.value, aliases, jitted, tainted):
                    tainted.add(stmt.targets[0].id)
                else:
                    tainted.discard(stmt.targets[0].id)
                continue
            bodies = [
                getattr(stmt, field)
                for field in ("body", "orelse", "finalbody")
                if isinstance(getattr(stmt, field, None), list)
            ]
            bodies += [h.body for h in getattr(stmt, "handlers", []) or []]
            if bodies and any(
                b and isinstance(b[0], ast.stmt) for b in bodies
            ):
                # compound statement: flag its header expressions, then
                # recurse into each body in source order (loop-body taint
                # carries to later statements of the same body)
                for field, value in ast.iter_fields(stmt):
                    if field in ("body", "orelse", "finalbody", "handlers"):
                        continue
                    values = value if isinstance(value, list) else [value]
                    for v in values:
                        if isinstance(v, ast.AST):
                            yield from self._flag_calls(v, aliases, tainted)
                for b in bodies:
                    yield from self._scan(b, aliases, jitted, tainted)
            else:
                yield from self._flag_calls(stmt, aliases, tainted)

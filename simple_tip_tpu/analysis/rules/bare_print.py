"""Rule ``bare-print``: ``print()`` in library code bypasses every log sink.

Library modules run inside spawned scheduler workers, fit-pool children and
capture harnesses whose stdout is a pipe nobody reads (or worse, a pipe a
JSON-line protocol owns — bench.py's one-line contract). A bare ``print``
there is either lost or corrupts a machine-readable stream, and it bypasses
the obs log bridge (simple_tip_tpu/obs/logbridge.py) that routes worker
``logger.*`` records into the telemetry event stream. Use the module logger
(or ``obs.event`` for structured telemetry) instead.

Exempt by design:

- the ``scripts/`` and ``tests/`` trees (their stdout IS the interface);
- entry-point modules inside the package (``cli.py``, ``__main__.py``):
  they are the package's script surface, argparse/stdout is their contract;
- test modules (``test_*.py``, ``conftest.py``) wherever they live.

Anything else needs an inline suppression with a justification.
"""

import ast
import os
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register

#: Analysis-root basenames whose whole tree is script/test surface.
EXEMPT_ROOTS = ("scripts", "tests")

#: Module basenames that are entry points (stdout is their contract).
EXEMPT_BASENAMES = ("cli.py", "__main__.py", "conftest.py")


def _exempt(module: ModuleInfo) -> bool:
    """Whether ``module`` is script/test/entry-point surface."""
    if os.path.basename(module.root) in EXEMPT_ROOTS:
        return True
    parts = module.relpath.split("/")
    if any(p in EXEMPT_ROOTS for p in parts[:-1]):
        return True
    base = parts[-1]
    return base in EXEMPT_BASENAMES or base.startswith("test_")


@register
class BarePrintRule(Rule):
    """Flag ``print()`` calls in library (non-script, non-entry-point) code."""

    name = "bare-print"
    description = (
        "print() in library code: lost in spawned workers, corrupts "
        "JSON-line protocols; use the module logger or obs events "
        "(scripts/tests/cli entry points exempt)"
    )
    tags = ('hygiene', 'logging')
    rationale = (
        "stdout in spawned scheduler/pool workers is a pipe nobody reads — or "
        "one a JSON-line protocol owns; route output through the module logger "
        "or obs events."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag bare print calls outside the exempt surfaces."""
        if _exempt(module):
            return
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                yield "", node.lineno, (
                    "print() in library code goes nowhere in spawned "
                    "workers and corrupts JSON-line stdout protocols; use "
                    "the module logger (routed to stderr + the obs stream "
                    "by obs.install_worker_logging) or obs.event()"
                )

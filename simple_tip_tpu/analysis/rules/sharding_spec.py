"""Rule ``sharding-spec-mismatch``: PartitionSpec axes must exist on a mesh.

A ``PartitionSpec`` naming an axis no mesh declares is the classic
pjit/shard_map deployment bug: nothing catches it at trace time on a
single-device dev box (the spec is dead weight there), and on the real pod
slice it explodes at dispatch — or worse, a typo'd axis silently means
"replicated" in contexts that tolerate unknown axes, so the program runs
with 1/N of the intended parallelism. The TF→JAX migration literature
(PAPERS.md) names sharding-spec drift as a dominant migration defect class.

Whole-program by construction: mesh axis names are declared where meshes
are BUILT (``parallel/ensemble.py`` ``Mesh(devs, (ENSEMBLE_AXIS,
DATA_AXIS))``, ``parallel/ring_attention.py`` ``Mesh(devs, ("sp",))``) while
``PartitionSpec`` literals appear wherever arrays are laid out — other
modules entirely. The project graph (``analysis.graph``) indexes both sides,
resolving axis-name strings through module-level constants and cross-module
imports of them.

Findings: every string axis in a ``PartitionSpec(...)`` literal that matches
no axis name of any mesh constructed anywhere in the analyzed project.

Conservatism: if ANY mesh site's axis tuple failed to resolve statically
(axis names computed at runtime), the rule stays silent — an unknown mesh
could declare the axis. Dynamic spec axes (variables, ``self.seq_axis``)
are likewise skipped. No mesh constructions at all → silent (nothing to
check against).
"""

from typing import Iterator, Sequence, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register


@register
class ShardingSpecMismatchRule(Rule):
    """Check PartitionSpec axis literals against constructed mesh axes."""

    name = "sharding-spec-mismatch"
    description = (
        "PartitionSpec axis names that match no axis of any mesh "
        "constructed in the analyzed project (cross-module, via the "
        "project graph)"
    )
    tags = ('sharding', 'cross-file')
    rationale = (
        "A typo'd axis fails at dispatch on the real pod slice — or silently "
        "means 'replicated', running at 1/N parallelism; invisible on a "
        "single-device dev box."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        """Check every resolved PartitionSpec axis against the mesh axes."""
        # Deferred import: analysis.graph itself imports rules.common, so a
        # module-level import here would cycle through rules/__init__.
        from simple_tip_tpu.analysis.graph import project_graph

        graph = project_graph(modules)
        if not graph.meshes:
            return
        if not all(site.complete for site in graph.meshes):
            return  # a dynamically-named mesh could declare anything
        known = set()
        for site in graph.meshes:
            known.update(site.axes)
        declared = ", ".join(sorted(known)) or "<none>"
        sites = ", ".join(
            sorted({f"{s.module.relpath}:{s.line}" for s in graph.meshes})
        )
        for spec in graph.specs:
            for axis in spec.axes:
                if axis in known:
                    continue
                yield spec.module.path, spec.line, (
                    f"PartitionSpec axis '{axis}' is not an axis of any "
                    f"mesh constructed in this project (declared axes: "
                    f"{declared}; meshes at {sites}); on a real mesh this "
                    "fails at dispatch or silently replicates"
                )

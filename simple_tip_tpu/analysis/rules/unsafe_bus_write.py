"""Rule ``unsafe-bus-write``: shared-bus paths demand atomic publication.

The artifact bus under ``$TIP_ASSETS`` is multi-process by design: the
resume journal, the SA fit cache, the AOT program cache, fleet leases/
heartbeats and the obs feature index are all read and written by
concurrent workers, bench children and schedulers. The repo's write
discipline for these files is settled (PR 6/11): either
``utils/artifacts_io.atomic_write_bytes`` (pid-unique tmp + fsync +
``os.replace``), the journal's fenced ``O_APPEND`` commit, or a plain
append whose readers tolerate one torn tail line. A *raw truncating*
``open(path, "w")`` on a bus path breaks every one of those contracts:
concurrent readers see a half-written file, and two writers sharing a
non-unique tmp name publish each other's torn output.

Detection is taint dataflow (``analysis/dataflow.py``): seeds are env
reads of bus roots (``TIP_JOURNAL``, ``TIP_OBS_INDEX``, ...), path
literals containing a bus segment (``journal/``, ``sa_fit_cache``,
``leases``...), and identifiers naming a bus artifact
(``manifest_path``, ``self.journal``); taint flows through assignments,
f-strings, ``os.path.join`` and helper returns (interprocedural
summaries: a function returning a bus-derived path taints its call
sites). A tainted path reaching ``open(..., "w"/"x"/"+")`` is a finding
— unless the path is pid-unique (its construction contains
``os.getpid()``/``mkstemp``/``uuid4``) *and* the function later
``os.replace``/``os.rename``s it: that is the atomic idiom itself.
Append mode is exempt (torn-tail-tolerant readers are the append bus
contract), and ``os.open``-based writers (the journal's fenced commit)
are out of scope by construction. Scripts and tests are exempt surfaces.
"""

import ast
from typing import Iterator, Optional, Sequence, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.bare_print import _exempt
from simple_tip_tpu.analysis.rules.common import callee_name

_OPEN_NAMES = ("open", "io.open", "builtins.open")


def _write_mode(call: ast.Call) -> Optional[str]:
    """The literal mode string when this ``open`` truncates or creates
    (``w``/``x``/``+``); None for reads, appends, or dynamic modes."""
    mode_node: Optional[ast.expr] = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if mode_node is None:
        return None  # default "r"
    if not (isinstance(mode_node, ast.Constant) and isinstance(mode_node.value, str)):
        return None
    mode = mode_node.value
    if "a" in mode:
        return None  # append bus: readers own the torn-tail contract
    if any(c in mode for c in "wx+"):
        return mode
    return None


@register
class UnsafeBusWriteRule(Rule):
    """Flag raw truncating writes of shared-bus-derived paths."""

    name = "unsafe-bus-write"
    description = (
        "a path derived from a shared-bus root (journal, sa_fit_cache, "
        "program cache, leases, obs index) reaches a raw truncating "
        "open() instead of atomic_write_bytes or the pid-unique "
        "tmp + os.replace idiom: concurrent readers see a half-written "
        "file and racing writers collide (scripts/tests exempt)"
    )
    tags = ('bus', 'concurrency', 'dataflow')
    rationale = (
        "Two fleet workers racing a plain truncating open interleave torn "
        "halves; readers see half-written JSON mid-publish — atomic replace (or "
        "append) is the only safe publish."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        """Taint every function body, flag tainted truncating opens."""
        # Deferred import: analysis.dataflow imports analysis.graph, which
        # imports rules.common — a module-level import here would cycle
        # through rules/__init__ (same pattern as sharding_spec).
        from simple_tip_tpu.analysis.dataflow import (
            Taint,
            TaintEnv,
            bus_seed,
            iter_function_nodes,
            project_flow,
            scope_walk,
        )

        pf = project_flow(modules)
        summaries = pf.seeded_return_summaries(lambda m: bus_seed(m, pf))
        for module in modules:
            if _exempt(module):
                continue
            aliases = pf.aliases(module)
            seed = bus_seed(module, pf)

            def call_effect(call, _arg_taint, _module=module):
                name = callee_name(call, aliases)
                fi = pf.graph.resolve_function(_module, name) if name else None
                if fi is not None and summaries.get(id(fi.node)):
                    return Taint(
                        chain=((call.lineno, f"{name}() returns a bus path"),)
                    )
                return None

            for fn in iter_function_nodes(module.tree):
                body = fn.body if isinstance(fn.body, list) else None
                if body is None:
                    continue  # lambda bodies can't open-and-write usefully
                env = TaintEnv(body, aliases, seed, call_effect)
                has_replace = self._has_replace(body, aliases)
                for stmt in body:
                    for node in scope_walk(stmt):
                        if not isinstance(node, ast.Call):
                            continue
                        if callee_name(node, aliases) not in _OPEN_NAMES:
                            continue
                        if not node.args:
                            continue
                        mode = _write_mode(node)
                        if mode is None:
                            continue
                        taint = env.expr_taint(node.args[0])
                        if taint is None:
                            continue
                        if taint.pid_unique and has_replace:
                            continue  # the atomic tmp+replace idiom itself
                        yield module.path, node.lineno, (
                            f"shared-bus path reaches a raw "
                            f"open(..., {mode!r}): {taint.render()} -> "
                            f"open at line {node.lineno}; concurrent "
                            f"readers can see the file half-written and "
                            f"racing writers collide — use "
                            f"utils/artifacts_io.atomic_write_bytes, or "
                            f"a pid-unique tmp "
                            f'(f"{{path}}.{{os.getpid()}}.tmp") + fsync '
                            f"+ os.replace"
                        )

    @staticmethod
    def _has_replace(body, aliases) -> bool:
        from simple_tip_tpu.analysis.dataflow import scope_walk

        for stmt in body:
            for node in scope_walk(stmt):
                if isinstance(node, ast.Call) and callee_name(
                    node, aliases
                ) in ("os.replace", "os.rename", "shutil.move"):
                    return True
        return False

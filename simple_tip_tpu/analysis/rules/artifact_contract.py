"""Rule ``artifact-contract``: the filesystem bus must not silently drift.

The engine phases and the result aggregation communicate exclusively through
the filesystem artifact bus (config.py docstring): engine writes
``priorities/{cs}_{ds}_{model}_{type}.npy``, timing pickles and AL pickles;
plotters and the completeness auditor parse those names back by underscore
splitting. Nothing ties the two sides together at runtime — a renamed field
or changed extension on one side produces an aggregation that silently reads
*nothing*. This rule makes the contract a lint invariant.

Model: a **bus** is a first-level artifact directory referenced via
``subdir("<name>")``, ``os.path.join(output_folder(), "<name>", ...)``,
``Path(output_folder()) / "<name>"`` or ``load_all_for_regex("<name>", ..)``.
Modules under ``engine/`` are the bus's writer side; modules under
``plotters/`` and ``utils/`` are its reader side. An f-string in the same
function scope as a bus reference that looks like an artifact filename
(``.npy``/``.pickle`` suffix, or suffix-less with >= 3 ``_``-separated
fields) is that bus's name template; a placeholder may expand to several
fields, so a writer template with W fields satisfies a reader expecting
R <= W fields of the same suffix.

Findings:

- a non-exempt bus written by engine with no reader in plotters/utils
  (orphaned artifacts), and vice versa (reader of a bus nobody writes);
- a reader name-template no writer template satisfies (and vice versa):
  suffix mismatch or reader expecting more fields than the writer emits.

Exempt buses: ``results`` (terminal plot/table output), ``models``
(engine-internal checkpoints), ``activations``/``.tmp`` (engine-internal
spill, bounded and self-consumed), ``sa_fit_cache`` and
``coverage_stats_cache`` (engine-internal cross-process caches, written AND
read by the engine — engine/sa_prep.py and engine/coverage_stats_cache.py;
plotters never touch them).
"""

import ast
import re
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.common import callee_name, import_aliases, parent_map

EXEMPT_BUSES = {
    "results",
    "models",
    "activations",
    ".tmp",
    "sa_fit_cache",
    "coverage_stats_cache",
    "program_cache",
}
WRITER_PREFIXES = ("engine/",)
READER_PREFIXES = ("plotters/", "utils/")
ARTIFACT_SUFFIXES = {".npy", ".pickle", ".pkl", ".msgpack"}

_SUFFIX_RE = re.compile(r"(\.[A-Za-z0-9]+)$")


@dataclass(frozen=True)
class _BusUse:
    bus: str
    relpath: str
    path: str  # absolute path, the driver's attribution key
    line: int


@dataclass(frozen=True)
class _Template:
    bus: str
    fields: int
    suffix: str
    relpath: str
    path: str  # absolute path, the driver's attribution key
    line: int
    text: str


def _enclosing_function(node: ast.AST, parents) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(cur)
    return None


def _bus_name_from_call(node: ast.Call, aliases) -> Optional[str]:
    """The bus name if this call references a first-level bus directory."""
    name = callee_name(node, aliases)
    tail = name.rsplit(".", 1)[-1] if name else None
    if tail == "subdir" and node.args:
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
    if tail == "load_all_for_regex" and node.args:
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
    if name in ("os.path.join", "posixpath.join") and len(node.args) >= 2:
        first, second = node.args[0], node.args[1]
        if (
            isinstance(first, ast.Call)
            and (callee_name(first, aliases) or "").rsplit(".", 1)[-1]
            == "output_folder"
            and isinstance(second, ast.Constant)
            and isinstance(second.value, str)
        ):
            return second.value
    return None


def _bus_name_from_binop(node: ast.BinOp, aliases) -> Optional[str]:
    """``Path(output_folder()) / "bus"`` pattern."""
    if not isinstance(node.op, ast.Div):
        return None
    if not (
        isinstance(node.right, ast.Constant) and isinstance(node.right.value, str)
    ):
        return None
    for sub in ast.walk(node.left):
        if isinstance(sub, ast.Call):
            tail = (callee_name(sub, aliases) or "").rsplit(".", 1)[-1]
            if tail == "output_folder":
                return node.right.value
    return None


def _fstring_template(node: ast.JoinedStr) -> Optional[Tuple[int, str, str]]:
    """(field count, suffix, rendered text) for artifact-shaped f-strings."""
    if not any(isinstance(v, ast.FormattedValue) for v in node.values):
        return None
    rendered: List[str] = []
    for value in node.values:
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            rendered.append(value.value)
        elif isinstance(value, ast.FormattedValue):
            rendered.append("\x00")  # one placeholder = one field
    text = "".join(rendered)
    if " " in text or "/" in text:
        return None
    # Regex patterns built as f-strings (reader-side matching) are not name
    # templates, and a real artifact name never has empty `_` fields.
    if any(ch in text for ch in "\\()[]*+?^$|"):
        return None
    m = _SUFFIX_RE.search(text)
    suffix = ""
    stem = text
    if m and not m.group(1)[1:].isdigit():
        suffix = m.group(1)
        stem = text[: -len(suffix)]
    parts = stem.split("_")
    if any(not p for p in parts):
        return None
    fields = len(parts)
    if suffix not in ARTIFACT_SUFFIXES and not (suffix == "" and fields >= 3):
        return None
    return fields, suffix, text.replace("\x00", "{}")


def _collect(modules: Sequence[ModuleInfo]):
    """(bus uses, templates) across all writer/reader modules."""
    uses: List[_BusUse] = []
    templates: List[_Template] = []
    for module in modules:
        side = _side(module.relpath)
        if side is None:
            continue
        aliases = import_aliases(module.tree)
        parents = parent_map(module.tree)
        scope_buses: Dict[Optional[ast.AST], List[_BusUse]] = {}
        scope_templates: Dict[Optional[ast.AST], List[Tuple[int, str, int, str]]] = {}
        for node in ast.walk(module.tree):
            bus = None
            if isinstance(node, ast.Call):
                bus = _bus_name_from_call(node, aliases)
            elif isinstance(node, ast.BinOp):
                bus = _bus_name_from_binop(node, aliases)
            if bus is not None:
                use = _BusUse(
                    bus=bus, relpath=module.relpath, path=module.path,
                    line=node.lineno,
                )
                uses.append(use)
                scope_buses.setdefault(
                    _enclosing_function(node, parents), []
                ).append(use)
            elif isinstance(node, ast.JoinedStr):
                t = _fstring_template(node)
                if t is not None:
                    scope_templates.setdefault(
                        _enclosing_function(node, parents), []
                    ).append((t[0], t[1], node.lineno, t[2]))
        for scope, found in scope_templates.items():
            for bus_use in scope_buses.get(scope, []):
                for fields, suffix, line, text in found:
                    templates.append(
                        _Template(
                            bus=bus_use.bus,
                            fields=fields,
                            suffix=suffix,
                            relpath=module.relpath,
                            path=module.path,
                            line=line,
                            text=text,
                        )
                    )
    return uses, templates


def _side(relpath: str) -> Optional[str]:
    if relpath.startswith(WRITER_PREFIXES):
        return "writer"
    if relpath.startswith(READER_PREFIXES):
        return "reader"
    return None


@register
class ArtifactContractRule(Rule):
    """Cross-check the engine→plotters filesystem artifact contract."""

    name = "artifact-contract"
    description = (
        "every artifact bus engine/ writes must have a reader in "
        "plotters//utils/ (and vice versa), with compatible filename "
        "templates (suffix + field arity)"
    )
    tags = ('bus', 'contract', 'cross-file')
    rationale = (
        "The filesystem bus filename templates are parsed by "
        "underscore-splitting; a writer and reader drifting apart makes aggregation "
        "silently read nothing."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        """Cross-check writer/reader bus uses and filename templates."""
        uses, templates = _collect(modules)
        if not uses:
            return

        writer_buses: Dict[str, _BusUse] = {}
        reader_buses: Dict[str, _BusUse] = {}
        for use in uses:
            side = _side(use.relpath)
            target = writer_buses if side == "writer" else reader_buses
            target.setdefault(use.bus, use)

        for bus, use in sorted(writer_buses.items()):
            if bus in EXEMPT_BUSES or bus in reader_buses:
                continue
            yield use.path, use.line, (
                f"engine writes artifact bus `{bus}` but no plotters/utils "
                "module reads it: orphaned artifacts (add a reader or exempt "
                "the bus)"
            )
        for bus, use in sorted(reader_buses.items()):
            if bus in EXEMPT_BUSES or bus in writer_buses:
                continue
            yield use.path, use.line, (
                f"`{bus}` is read by aggregation but no engine module writes "
                "it: the reader can only ever see an empty bus"
            )

        writer_templates: Dict[str, List[_Template]] = {}
        reader_templates: Dict[str, List[_Template]] = {}
        for t in templates:
            if t.bus in EXEMPT_BUSES:
                continue
            side = _side(t.relpath)
            bucket = writer_templates if side == "writer" else reader_templates
            bucket.setdefault(t.bus, []).append(t)

        for bus, readers in sorted(reader_templates.items()):
            writers = writer_templates.get(bus)
            if not writers:
                continue
            for rt in readers:
                if not any(
                    wt.suffix == rt.suffix and wt.fields >= rt.fields
                    for wt in writers
                ):
                    options = ", ".join(
                        sorted({f"{wt.text} ({wt.relpath})" for wt in writers})
                    )
                    yield rt.path, rt.line, (
                        f"reader template `{rt.text}` on bus `{bus}` matches "
                        f"no writer template (writers emit: {options}): "
                        "filename contract drift"
                    )
        for bus, writers in sorted(writer_templates.items()):
            readers = reader_templates.get(bus)
            if not readers:
                continue
            for wt in writers:
                if not any(
                    wt.suffix == rt.suffix and wt.fields >= rt.fields
                    for rt in readers
                ):
                    options = ", ".join(
                        sorted({f"{rt.text} ({rt.relpath})" for rt in readers})
                    )
                    yield wt.path, wt.line, (
                        f"writer template `{wt.text}` on bus `{bus}` is "
                        f"parseable by no reader template (readers expect: "
                        f"{options}): filename contract drift"
                    )

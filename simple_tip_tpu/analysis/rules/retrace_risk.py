"""Rule ``retrace-risk``: jitted callables rebuilt per iteration or per call.

The fused run-program layer exists to give each (case-study, model-group,
badge-shape) ONE compiled program; the failure mode it must not reintroduce
is the silent per-badge retrace. Two syntactic shapes produce it:

1. transform construction inside a loop body::

       for badge in badges:
           fn = jax.jit(score)        # fresh PjitFunction per iteration
           out.append(fn(badge))      # ...so every call traces from scratch

   jit caches traces on the *callable object*; a new object per iteration
   has an empty cache every time. The fix is hoisting the construction out
   of the loop (or module level), as models/train.py's lru_cached factories
   do.

2. inline construct-and-call::

       out = jax.jit(score)(badge)    # the traced program is dropped here

   the jitted object lives for one call, so a second execution of the
   enclosing statement retraces — the same defect with the loop supplied by
   the caller.

Both flag regardless of what the arguments are: a callable whose trace
cache cannot outlive one iteration is a retrace risk even when today's
shapes happen to be constant (the per-badge Python-scalar key — ``valid``
counts, remainder badge sizes — is exactly what creeps in next).

3. per-member unroll of a stacked pytree inside traced code::

       @jax.jit
       def group_chain(stacked, x):
           for g in range(GROUP):
               member = jax.tree.map(lambda l: l[g], stacked)
               out.append(apply(member, x))

   the grouped executor's anti-pattern: indexing a stacked member axis
   with a Python loop variable inside a trace unrolls the group into G
   per-member subgraphs — G copies of the chain compiled and dispatched
   where ONE vmapped program (``ops/fused_chain.make_group_chain_fn``)
   was the point. Flagged when a tree-map-family call inside a loop in
   jit-reachable code subscripts by the loop variable; the host-side
   fan-out that slices RESULTS after the dispatch is untraced and does
   not flag.

Only the JIT FAMILY is tracked (``jax.jit``/``jax.pjit``/``jax.pmap``):
those are the transforms that own an XLA compile cache keyed on the
callable object. Trace-time combinators (``vmap``, ``grad``,
``pallas_call``, ``lax.scan``) constructed inline are idiomatic — they
trace as part of whatever program encloses them and carry no cache to
lose. For the same reason, a jit constructed INSIDE an already
jit-reachable function is excluded (nested jit is inlined into the outer
trace), and decorated defs inside loops are fine; only transform CALL
expressions are tracked.
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.common import (
    callee_name,
    dotted,
    import_aliases,
    is_partial_of,
    jit_reachable_functions,
    parent_map,
)

#: Transforms whose result owns a compile cache (the retrace-able kind).
_JIT_FAMILY = {
    "jax.jit",
    "jax.pjit",
    "jax.pmap",
    "jax.experimental.pjit.pjit",
}

#: Per-leaf pytree mappers: subscripting a stacked member axis through one
#: of these with a loop variable inside a trace unrolls the group axis.
_TREE_MAP_FAMILY = {
    "jax.tree.map",
    "jax.tree_map",
    "jax.tree_util.tree_map",
}


def _is_jit_construction(node: ast.Call, aliases) -> bool:
    """A call expression that BUILDS a compile-cached callable.

    Covers ``jax.jit(f)``, ``partial(jax.jit, ...)(f)`` and
    ``jax.jit(static_argnames=...)``-style configured constructions.
    """
    name = callee_name(node, aliases)
    if name in _JIT_FAMILY:
        return True
    func = node.func
    if isinstance(func, ast.Call):
        if callee_name(func, aliases) in _JIT_FAMILY:
            return True
        return any(is_partial_of(func, t, aliases) for t in _JIT_FAMILY)
    return False


@register
class RetraceRiskRule(Rule):
    """Flag jit/vmap/etc. construction inside loops and construct-and-call."""

    name = "retrace-risk"
    description = (
        "JAX transform constructed inside a loop body or immediately "
        "called inline: the traced-callable cache dies with the object, so "
        "every iteration/call retraces — hoist the construction (module "
        "level, __init__, or an lru_cached factory)"
    )
    tags = ('perf', 'traced')
    rationale = (
        "A fresh jitted callable per iteration has an empty compile cache, so "
        "every iteration retraces — the per-badge retrace the program cache "
        "exists to prevent."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Walk call expressions; flag jit constructions whose compile
        cache cannot outlive one loop iteration or one statement."""
        aliases = import_aliases(module.tree)
        parents = parent_map(module.tree)
        traced = jit_reachable_functions(module.tree, aliases)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if callee_name(node, aliases) in _TREE_MAP_FAMILY:
                yield from self._member_unroll(node, parents, traced, aliases)
                continue
            inline = isinstance(node.func, ast.Call) and _is_jit_construction(
                node.func, aliases
            )
            construction = inline or _is_jit_construction(node, aliases)
            if not construction:
                continue
            if self._inside_traced(node, parents, traced):
                continue  # nested jit inlines into the enclosing trace
            if inline:
                name = dotted(node.func.func, aliases) or "jax.jit"
                yield "", node.lineno, (
                    f"{name}(...) constructed and called inline: the "
                    "compiled program is discarded after this call and "
                    "every execution retraces; bind the jitted callable "
                    "once and reuse it"
                )
                continue
            # jit construction inside a for/while body — but not when a
            # def/lambda boundary sits between the loop and the call (the
            # nested function may be constructed once and called later)
            walker = parents.get(node)
            while walker is not None:
                if isinstance(
                    walker,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    break
                if isinstance(walker, (ast.For, ast.AsyncFor, ast.While)):
                    name = dotted(node.func, aliases) or "jax.jit"
                    yield "", node.lineno, (
                        f"{name}(...) constructed inside a loop body: a "
                        "fresh jitted callable per iteration has an empty "
                        "compile cache, so every iteration retraces (the "
                        "per-badge retrace the program cache exists to "
                        "prevent); hoist the construction out of the loop"
                    )
                    break
                walker = parents.get(walker)

    def _member_unroll(self, node, parents, traced, aliases):
        """Flag a tree-map call that slices a stacked pytree by a Python
        loop variable inside jit-reachable code (the group-unroll shape).

        Host-side code never flags (the fan-out after a grouped dispatch
        legitimately slices results per member); a def boundary between
        the loop and the call clears the loop variables (the nested
        function may run once per group outside the loop).
        """
        if not self._inside_traced(node, parents, traced):
            return
        loop_vars = set()
        walker = parents.get(node)
        while walker is not None:
            if isinstance(walker, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if isinstance(walker, (ast.For, ast.AsyncFor)):
                loop_vars.update(
                    n.id
                    for n in ast.walk(walker.target)
                    if isinstance(n, ast.Name)
                )
            if isinstance(
                walker,
                (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
            ):
                for gen in walker.generators:
                    loop_vars.update(
                        n.id
                        for n in ast.walk(gen.target)
                        if isinstance(n, ast.Name)
                    )
            walker = parents.get(walker)
        if not loop_vars:
            return
        lambdas = [a for a in node.args if isinstance(a, ast.Lambda)]
        lambdas += [
            kw.value for kw in node.keywords if isinstance(kw.value, ast.Lambda)
        ]
        for lam in lambdas:
            for sub in ast.walk(lam.body):
                if not isinstance(sub, ast.Subscript):
                    continue
                if any(
                    isinstance(n, ast.Name) and n.id in loop_vars
                    for n in ast.walk(sub.slice)
                ):
                    name = dotted(node.func, aliases) or "jax.tree.map"
                    yield "", node.lineno, (
                        f"{name}(...) slices a stacked pytree by a loop "
                        "variable inside traced code: the member loop "
                        "unrolls into one subgraph per member — G compiles "
                        "and G dispatches where one vmapped program "
                        "(ops/fused_chain.make_group_chain_fn) does the "
                        "whole group; vmap over the stacked axis instead"
                    )
                    return

    @staticmethod
    def _inside_traced(node, parents, traced) -> bool:
        walker = parents.get(node)
        while walker is not None:
            if walker in traced:
                return True
            walker = parents.get(walker)
        return False

"""Shared AST machinery for the JAX-aware tiplint rules.

Provides import-alias resolution (``jnp`` -> ``jax.numpy``), dotted-name
rendering for call/attribute chains, and the *jit-reachability* analysis that
decides which function bodies are traced device code.

Jit-reachability is an intentionally local, syntactic over/under-approximation
(no call-graph, no cross-module dataflow). A function is jit-reachable when:

1. it is decorated with a JAX transform (``@jax.jit``, ``@jax.vmap``,
   ``@functools.partial(jax.jit, ...)``, ...);
2. it (or a lambda) is passed by name into a transform call in the same
   module (``jax.jit(f)``, ``jax.vmap(f)``, ``jax.lax.scan(step, ...)``);
3. its body uses ``jax.lax`` control flow (``scan``/``while_loop``/
   ``fori_loop``/``cond``/``map``) — functions structured around lax control
   flow are device code even when the jit wrapper is applied by a factory in
   another function (the ``make_epoch_core`` pattern in models/train.py);
4. it is nested inside a jit-reachable function.
"""

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

#: Canonical names whose call traces the callable passed to them.
TRANSFORM_CALLEES = {
    "jax.jit",
    "jax.pjit",
    "jax.vmap",
    "jax.pmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.experimental.pjit.pjit",
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.map",
    "jax.lax.switch",
}

#: lax control-flow callees whose presence marks the *enclosing* function as
#: device code (heuristic 3 above).
LAX_CONTROL_FLOW = {
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.map",
    "jax.lax.switch",
}


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """local name -> canonical dotted module/object path, from all imports.

    ``import jax.numpy as jnp`` maps ``jnp -> jax.numpy``; ``from jax import
    random`` maps ``random -> jax.random``; ``from functools import partial``
    maps ``partial -> functools.partial``. Imports anywhere in the file count
    (this codebase imports jax lazily inside functions by design).
    """
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a Name/Attribute chain, or None.

    ``jnp.sqrt`` -> ``jax.numpy.sqrt`` under ``import jax.numpy as jnp``.
    """
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    head = aliases.get(node.id, node.id)
    parts.append(head)
    return ".".join(reversed(parts))


def callee_name(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a call's target (None for computed callees)."""
    return dotted(call.func, aliases)


def is_partial_of(call: ast.Call, target: str, aliases: Dict[str, str]) -> bool:
    """True for ``functools.partial(<target>, ...)`` call expressions."""
    name = callee_name(call, aliases)
    if name not in ("functools.partial", "partial"):
        return False
    return bool(call.args) and dotted(call.args[0], aliases) == target


def _transform_target(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """Does this decorator/callee expression denote a JAX transform?"""
    name = dotted(node, aliases)
    if name in TRANSFORM_CALLEES:
        return True
    if isinstance(node, ast.Call):
        # @partial(jax.jit, ...) / partial(jax.vmap, ...)(f)
        for t in TRANSFORM_CALLEES:
            if is_partial_of(node, t, aliases):
                return True
        # @jax.jit(static_argnames=...) — a transform called with config only
        inner = callee_name(node, aliases)
        if inner in TRANSFORM_CALLEES:
            return True
    return False


def parent_map(tree: ast.Module) -> Dict[ast.AST, ast.AST]:
    """child node -> parent node for the whole tree."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def jit_reachable_functions(
    tree: ast.Module, aliases: Dict[str, str]
) -> Set[FunctionNode]:
    """The set of function/lambda nodes considered traced device code."""
    parents = parent_map(tree)
    defs_by_name: Dict[str, List[FunctionNode]] = {}
    all_funcs: List[FunctionNode] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
            all_funcs.append(node)
        elif isinstance(node, ast.Lambda):
            all_funcs.append(node)

    reachable: Set[FunctionNode] = set()

    # (1) decorated with a transform
    for fn in all_funcs:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_transform_target(d, aliases) for d in fn.decorator_list):
                reachable.add(fn)

    # (2) passed (by name or inline) into a transform call
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not _transform_target(node.func, aliases):
            continue
        for arg in node.args:
            if isinstance(arg, ast.Lambda):
                reachable.add(arg)
            elif isinstance(arg, ast.Name):
                for fn in defs_by_name.get(arg.id, []):
                    reachable.add(fn)

    # (3) body uses lax control flow
    for fn in all_funcs:
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    if callee_name(node, aliases) in LAX_CONTROL_FLOW:
                        reachable.add(fn)

    # (4) nested defs inside reachable functions
    changed = True
    while changed:
        changed = False
        for fn in all_funcs:
            if fn in reachable:
                continue
            node: Optional[ast.AST] = parents.get(fn)
            while node is not None:
                if node in reachable:
                    reachable.add(fn)
                    changed = True
                    break
                node = parents.get(node)

    return reachable


def function_body_nodes(fn: FunctionNode) -> Iterator[ast.AST]:
    """Walk every node of a function body (the def node itself excluded)."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        yield from ast.walk(stmt)


def resolve_local_function(
    name: str, tree: ast.Module
) -> Optional[Union[ast.FunctionDef, ast.AsyncFunctionDef]]:
    """A def with this name anywhere in the module (first match), or None."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == name:
                return node
    return None


def lambda_or_def_params(fn: FunctionNode) -> List[str]:
    """Positional/keyword parameter names of a function or lambda."""
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def contains_jnp(node: ast.AST, aliases: Dict[str, str]) -> Optional[Tuple[int, str]]:
    """(line, dotted name) of the first jax/jnp reference inside ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Attribute, ast.Name)):
            name = dotted(sub, aliases)
            if name and (name.startswith("jax.numpy.") or name == "jax.numpy"):
                return getattr(sub, "lineno", 0), name
    return None

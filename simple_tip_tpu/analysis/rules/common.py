"""Shared AST machinery for the JAX-aware tiplint rules.

Provides import-alias resolution (``jnp`` -> ``jax.numpy``), dotted-name
rendering for call/attribute chains, and the *jit-reachability* analysis that
decides which function bodies are traced device code.

Jit-reachability is an intentionally local, syntactic over/under-approximation
(no cross-module dataflow — the project graph in ``analysis.graph`` layers
that on top). A function is jit-reachable when:

1. it is decorated with a JAX transform (``@jax.jit``, ``@jax.vmap``,
   ``@functools.partial(jax.jit, ...)``, ...);
2. it (or a lambda) is passed into a transform call in the same module —
   by name (``jax.jit(f)``, ``jax.lax.scan(step, ...)``), through
   ``functools.partial(f, ...)``, or via a local ``g = partial(f, ...)``
   binding later passed in (``jax.shard_map(g, ...)``); shard_map and
   ``pallas_call`` count as transforms — their callees are traced device
   code;
3. its body uses ``jax.lax`` control flow (``scan``/``while_loop``/
   ``fori_loop``/``cond``/``map``) or a cross-device collective
   (``ppermute``/``all_to_all``/``psum``/...) — such functions are device
   code even when the jit/shard_map wrapper is applied by a factory in
   another function (the ``make_epoch_core`` pattern in models/train.py);
4. it is nested inside a jit-reachable function.
"""

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

#: Canonical names whose call traces the callable passed to them.
TRANSFORM_CALLEES = {
    "jax.jit",
    "jax.pjit",
    "jax.vmap",
    "jax.pmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.experimental.pjit.pjit",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.experimental.pallas.pallas_call",
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.map",
    "jax.lax.switch",
}

#: lax control-flow callees whose presence marks the *enclosing* function as
#: device code (heuristic 3 above).
LAX_CONTROL_FLOW = {
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.map",
    "jax.lax.switch",
}

#: Cross-device collectives: they require a bound mesh axis name, so a
#: function calling one can ONLY execute as traced device code under
#: shard_map/pmap — the same enclosing-function marker as lax control flow
#: (heuristic 3), covering collectives-only bodies like ulysses' all-to-all
#: re-shard that carry no lax control flow of their own.
LAX_COLLECTIVES = {
    "jax.lax.ppermute",
    "jax.lax.pshuffle",
    "jax.lax.all_to_all",
    "jax.lax.all_gather",
    "jax.lax.psum",
    "jax.lax.psum_scatter",
    "jax.lax.pmean",
    "jax.lax.pmax",
    "jax.lax.pmin",
    "jax.lax.axis_index",
    "jax.lax.pvary",
    "jax.lax.pcast",
}


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """local name -> canonical dotted module/object path, from all imports.

    ``import jax.numpy as jnp`` maps ``jnp -> jax.numpy``; ``from jax import
    random`` maps ``random -> jax.random``; ``from functools import partial``
    maps ``partial -> functools.partial``. Imports anywhere in the file count
    (this codebase imports jax lazily inside functions by design).
    """
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a Name/Attribute chain, or None.

    ``jnp.sqrt`` -> ``jax.numpy.sqrt`` under ``import jax.numpy as jnp``.
    """
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    head = aliases.get(node.id, node.id)
    parts.append(head)
    return ".".join(reversed(parts))


def callee_name(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a call's target (None for computed callees)."""
    return dotted(call.func, aliases)


def is_partial_of(call: ast.Call, target: str, aliases: Dict[str, str]) -> bool:
    """True for ``functools.partial(<target>, ...)`` call expressions."""
    name = callee_name(call, aliases)
    if name not in ("functools.partial", "partial"):
        return False
    return bool(call.args) and dotted(call.args[0], aliases) == target


def _transform_target(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """Does this decorator/callee expression denote a JAX transform?"""
    name = dotted(node, aliases)
    if name in TRANSFORM_CALLEES:
        return True
    if isinstance(node, ast.Call):
        # @partial(jax.jit, ...) / partial(jax.vmap, ...)(f)
        for t in TRANSFORM_CALLEES:
            if is_partial_of(node, t, aliases):
                return True
        # @jax.jit(static_argnames=...) — a transform called with config only
        inner = callee_name(node, aliases)
        if inner in TRANSFORM_CALLEES:
            return True
    return False


def name_bindings(tree: ast.Module) -> Dict[str, List[ast.expr]]:
    """name -> every expression assigned to it via a simple ``name = expr``.

    All assignments to a name are kept (a name bound in both branches of an
    ``if`` — the ``shard_fn = partial(...)`` pattern in models/transformer.py
    — must resolve to every candidate, not just the last)."""
    bindings: Dict[str, List[ast.expr]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name):
                bindings.setdefault(target.id, []).append(node.value)
    return bindings


def callable_targets(
    expr: ast.AST,
    aliases: Dict[str, str],
    bindings: Dict[str, List[ast.expr]],
    _depth: int = 0,
) -> Tuple[Set[str], Set[ast.Lambda]]:
    """(dotted names, lambda nodes) an expression may denote as a callable.

    Unwraps ``functools.partial(f, ...)`` to ``f``, follows simple local
    ``name = <callable expr>`` bindings one level at a time (bounded depth),
    and resolves names through the module's import aliases — so
    ``shard_fn = partial(ulysses_attention, ...)`` followed by
    ``jax.shard_map(shard_fn, ...)`` reports ``ulysses_attention``'s dotted
    name as a traced target."""
    names: Set[str] = set()
    lambdas: Set[ast.Lambda] = set()
    if _depth > 4:
        return names, lambdas
    if isinstance(expr, ast.Lambda):
        lambdas.add(expr)
    elif isinstance(expr, ast.Name):
        names.add(aliases.get(expr.id, expr.id))
        for bound in bindings.get(expr.id, []):
            sub_names, sub_lambdas = callable_targets(
                bound, aliases, bindings, _depth + 1
            )
            names |= sub_names
            lambdas |= sub_lambdas
    elif isinstance(expr, ast.Attribute):
        name = dotted(expr, aliases)
        if name:
            names.add(name)
    elif isinstance(expr, ast.Call):
        callee = callee_name(expr, aliases)
        if callee in ("functools.partial", "partial") and expr.args:
            sub_names, sub_lambdas = callable_targets(
                expr.args[0], aliases, bindings, _depth + 1
            )
            names |= sub_names
            lambdas |= sub_lambdas
    return names, lambdas


def parent_map(tree: ast.Module) -> Dict[ast.AST, ast.AST]:
    """child node -> parent node for the whole tree."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def jit_reachable_functions(
    tree: ast.Module, aliases: Dict[str, str]
) -> Set[FunctionNode]:
    """The set of function/lambda nodes considered traced device code."""
    parents = parent_map(tree)
    defs_by_name: Dict[str, List[FunctionNode]] = {}
    all_funcs: List[FunctionNode] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
            all_funcs.append(node)
        elif isinstance(node, ast.Lambda):
            all_funcs.append(node)

    reachable: Set[FunctionNode] = set()
    bindings = name_bindings(tree)

    # (1) decorated with a transform
    for fn in all_funcs:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_transform_target(d, aliases) for d in fn.decorator_list):
                reachable.add(fn)

    # (2) passed into a transform call — by name, inline lambda, through a
    # functools.partial wrapper, or via a local `name = partial(f, ...)`
    # binding (the shard_map dispatch pattern in models/transformer.py)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not _transform_target(node.func, aliases):
            continue
        for arg in node.args:
            names, lambdas = callable_targets(arg, aliases, bindings)
            reachable.update(lambdas)
            for name in names:
                for fn in defs_by_name.get(name.rsplit(".", 1)[-1], []):
                    reachable.add(fn)

    # (3) body uses lax control flow or a cross-device collective (the
    # latter requires a bound mesh axis, i.e. shard_map/pmap tracing)
    for fn in all_funcs:
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    name = callee_name(node, aliases)
                    if name in LAX_CONTROL_FLOW or name in LAX_COLLECTIVES:
                        reachable.add(fn)

    # (4) nested defs inside reachable functions
    changed = True
    while changed:
        changed = False
        for fn in all_funcs:
            if fn in reachable:
                continue
            node: Optional[ast.AST] = parents.get(fn)
            while node is not None:
                if node in reachable:
                    reachable.add(fn)
                    changed = True
                    break
                node = parents.get(node)

    return reachable


def function_body_nodes(fn: FunctionNode) -> Iterator[ast.AST]:
    """Walk every node of a function body (the def node itself excluded)."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        yield from ast.walk(stmt)


def resolve_local_function(
    name: str, tree: ast.Module
) -> Optional[Union[ast.FunctionDef, ast.AsyncFunctionDef]]:
    """A def with this name anywhere in the module (first match), or None."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == name:
                return node
    return None


def lambda_or_def_params(fn: FunctionNode) -> List[str]:
    """Positional/keyword parameter names of a function or lambda."""
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def contains_jnp(node: ast.AST, aliases: Dict[str, str]) -> Optional[Tuple[int, str]]:
    """(line, dotted name) of the first jax/jnp reference inside ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Attribute, ast.Name)):
            name = dotted(sub, aliases)
            if name and (name.startswith("jax.numpy.") or name == "jax.numpy"):
                return getattr(sub, "lineno", 0), name
    return None

"""Rule ``escaping-tracer``: traced values must not outlive the trace.

Inside a jit/shard_map/scan trace every parameter-derived (or jnp-built)
value is a Tracer, not an array. Stashing one somewhere that survives the
trace — a module global, an enclosing function's cell via ``nonlocal``, a
``self.`` attribute — is the classic JAX leak: at best
``UnexpectedTracerError`` on the next touch, at worst a silently stale
concrete value baked in from trace time (the cache "works" until shapes
or weights change). The side effect also silently disappears on retrace,
so even host-side bookkeeping written this way is wrong.

Traced bodies come from the project graph: locally jit-reachable
functions *plus* functions traced from another module (a shard_map or
``pallas_call`` boundary elsewhere). Taintedness is dataflow
(``analysis/dataflow.py``): parameters seed the taint, jax/jnp call
results count as traced values, assignment chains propagate with
provenance — so the finding message renders the chain from the traced
parameter to the escaping store. Constant stores (``self.calls += 1`` on
a plain int, ``self.debug = True``) stay clean: only tainted values flag.
"""

import ast
from typing import Dict, Iterator, Optional, Sequence, Set, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.common import (
    FunctionNode,
    callee_name,
    lambda_or_def_params,
)


def _jax_seed(aliases: Dict[str, str]):
    """Seed callback: jax/jnp call results are traced values under trace."""

    def seed(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Call):
            name = callee_name(node, aliases)
            if name and (name == "jax" or name.startswith("jax.")):
                return f"`{name}(...)` result"
        return None

    return seed


@register
class EscapingTracerRule(Rule):
    """Flag traced values stored where they outlive the trace."""

    name = "escaping-tracer"
    description = (
        "a traced-context value (parameter-derived or jnp-built) is "
        "assigned to a module global, a nonlocal cell, or a self. "
        "attribute inside a traced function: the Tracer outlives the "
        "trace (UnexpectedTracerError, or a silently stale value baked "
        "in at trace time)"
    )
    tags = ('traced', 'interprocedural', 'correctness')
    rationale = (
        "An escaped tracer outlives its trace: the next use raises "
        "UnexpectedTracerError at best — at worst it silently bakes one trace's "
        "constant into every later call."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        """Taint-check every store in every traced function body."""
        # Deferred imports: analysis.graph/.dataflow import rules.common, so
        # module-level imports here would cycle through rules/__init__
        # (same pattern as sharding_spec).
        from simple_tip_tpu.analysis.dataflow import project_flow
        from simple_tip_tpu.analysis.graph import project_graph

        graph = project_graph(modules)
        traced: Dict[int, Set[FunctionNode]] = {}
        how: Dict[int, str] = {}
        for m in modules:
            traced[id(m)] = set(graph.jit_reachable(m))
        for fi, boundary in graph.traced_entries():
            traced.setdefault(id(fi.module), set()).add(fi.node)
            if boundary is not None:
                how[id(fi.node)] = (
                    f"traced via {boundary.transform} at "
                    f"{boundary.module.relpath}:{boundary.line}"
                )
        pf = project_flow(modules)
        for module in modules:
            aliases = pf.aliases(module)
            for fn in sorted(
                traced.get(id(module), ()), key=lambda f: f.lineno
            ):
                if isinstance(fn, ast.Lambda):
                    continue  # lambdas cannot contain statements that store
                label = how.get(id(fn), "locally jit-reachable")
                yield from self._check_fn(module, fn, aliases, label)

    def _check_fn(
        self,
        module: ModuleInfo,
        fn: FunctionNode,
        aliases: Dict[str, str],
        traced_how: str,
    ) -> Iterator[Tuple[str, int, str]]:
        from simple_tip_tpu.analysis.dataflow import (
            Taint,
            TaintEnv,
            scope_walk,
        )

        params = {
            p: Taint(chain=((fn.lineno, f"traced parameter `{p}`"),))
            for p in lambda_or_def_params(fn)
            if p not in ("self", "cls")
        }
        env = TaintEnv(fn.body, aliases, _jax_seed(aliases), param_taints=params)
        escapes: Set[str] = set()
        for stmt in fn.body:
            for node in scope_walk(stmt):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    escapes.update(node.names)
        name = getattr(fn, "name", "<lambda>")
        for stmt in fn.body:
            for node in scope_walk(stmt):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = [(t, node.value) for t in node.targets]
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets = [(node.target, node.value)]
                elif isinstance(node, ast.AugAssign):
                    targets = [(node.target, node.value)]
                for target, value in targets:
                    taint = env.expr_taint(value)
                    if taint is None:
                        continue
                    sink = self._escape_sink(target, escapes)
                    if sink is None:
                        continue
                    yield module.path, node.lineno, (
                        f"traced value escapes `{name}` ({traced_how}) "
                        f"through {sink}: {taint.render()} -> stored at "
                        f"line {node.lineno}; the Tracer outlives the "
                        f"trace — return the value instead of storing it"
                    )

    @staticmethod
    def _escape_sink(target: ast.expr, escapes: Set[str]) -> Optional[str]:
        """A description of the escaping store target, or None if local."""
        if isinstance(target, ast.Name) and target.id in escapes:
            return f"global/nonlocal `{target.id}`"
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            return f"attribute `self.{target.attr}`"
        if isinstance(target, ast.Subscript):
            base = target.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                return f"container `self.{base.attr}[...]`"
            if isinstance(base, ast.Name) and base.id in escapes:
                return f"container `{base.id}[...]`"
        return None

"""Rule ``f64-on-tpu``: float64 in device-adjacent modules downcasts on TPU.

TPUs have no native f64: without ``jax_enable_x64`` a ``float64`` request
silently becomes f32 on device, and with it, emulated f64 is an order of
magnitude slower. Host-side numpy f64 is legitimate where exactness parity
with the reference matters (the KDE in ``ops/kde.py`` is the documented
example — README "Architecture"), but every such site must be explicit: an
allowlisted module or an inline suppression with a justification comment,
so a future device-migration sweep can find them all.

Flags, in device-adjacent modules (``ops/``, ``parallel/``, ``models/``,
``engine/``, ``casestudies/``) outside the allowlist:

- any ``<x>.float64`` attribute (``np.float64``, ``jnp.float64``);
- any ``"float64"``/``"f64"`` string literal used as a call argument or in
  a comparison (dtype strings), excluding docstrings.
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register

#: Module prefixes where f64 matters (device-adjacent code).
DEVICE_ADJACENT_PREFIXES = (
    "ops/",
    "parallel/",
    "models/",
    "engine/",
    "casestudies/",
)

#: Modules whose f64 is wholesale intentional (host-exactness by design).
ALLOWLIST = ("ops/kde.py",)

_DTYPE_STRINGS = {"float64", "f64"}


@register
class F64OnTpuRule(Rule):
    """Flag float64 dtypes outside the explicit host-f64 allowlist."""

    name = "f64-on-tpu"
    description = (
        "float64 dtype usage in device-adjacent modules (TPUs have no "
        "native f64; requests silently downcast) outside the allowlist"
    )
    tags = ('dtype', 'tpu')
    rationale = (
        "TPUs have no native f64 — requests silently downcast to f32, or run an "
        "order of magnitude slower under x64 emulation."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag float64 dtype requests in device-adjacent modules."""
        if not module.relpath.startswith(DEVICE_ADJACENT_PREFIXES):
            return
        if module.relpath in ALLOWLIST:
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                yield "", node.lineno, (
                    "float64 dtype in a device-adjacent module: TPUs have no "
                    "native f64 (silent downcast to f32); use f32/bf16 on "
                    "device, or suppress with a host-exactness justification"
                )
            elif isinstance(node, ast.Call):
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if (
                        isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and arg.value in _DTYPE_STRINGS
                    ):
                        yield "", arg.lineno, (
                            f'dtype string "{arg.value}" in a device-adjacent '
                            "module: TPUs have no native f64 (silent downcast "
                            "to f32)"
                        )

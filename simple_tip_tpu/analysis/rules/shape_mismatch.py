"""Rule ``shape-mismatch``: statically incompatible array shapes.

The tipcheck abstract interpreter (``analysis.shapes``) propagates symbolic
``(dims, dtype, spec)`` values through the project graph — from declared
entry contracts, jit/pjit/vmap/shard_map boundaries, and module top-level
code — and evaluates the jnp vocabulary's transfer functions on the way.
This rule surfaces the interpreter's shape contradictions:

- ``reshape`` targets that change the element count,
- ``matmul``/``@``/``einsum`` contracting or index-binding conflicts,
- ``concatenate``/``stack`` operands disagreeing off the join axis,
- broadcasting two dims that are both known, unequal, and neither 1,
- ``fori_loop``/``while_loop``/``scan`` carries that change shape or
  structure between iterations.

Every finding carries an ``inferred:`` provenance chain (like the dataflow
taint chains) showing how the offending shape was derived, hop by hop.

Conservatism: any dim the interpreter cannot pin becomes ``Dyn`` and every
check involving a ``Dyn`` stays silent, so meshes sized from
``jax.device_count()`` or env vars can never create false positives.
"""

from typing import Iterator, Sequence, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register


@register
class ShapeMismatchRule(Rule):
    """Surface shape contradictions found by the abstract interpreter."""

    name = "shape-mismatch"
    description = (
        "statically incompatible shapes (reshape/matmul/einsum/concat/"
        "broadcast/loop-carry) under the inferred symbolic shapes"
    )
    tags = ("tipcheck", "shapes", "semantic", "interprocedural")
    rationale = (
        "A wrong reshape or einsum inside jit fails only when the traced "
        "path executes — on the pod slice, not the dev box. Abstract "
        "interpretation over the project graph catches the contradiction "
        "at lint time, with the inference chain attached."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        from simple_tip_tpu.analysis.shapes import project_shapes

        for f in project_shapes(modules).findings:
            if f.kind == self.name:
                yield f.module.path, f.line, f.message

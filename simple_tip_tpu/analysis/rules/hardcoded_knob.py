"""Rule ``hardcoded-knob``: library code must not pin planner-owned knobs.

The execution planner (``simple_tip_tpu/plan/``) owns the repo's tuning
surface: every knob in its registry (``plan/knobs.py``,
``planned_env_vars()``) is searched against the learned cost model, and
the chosen assignment is applied through an ExecutionPlan. A library
module that writes one of those env vars into ``os.environ`` directly
silently overrides whatever the plan chose — invisible to ``plan
explain``, unattributable in the plan-vs-actual audit, and undiscoverable
by the next person staring at a study that ignores its plan.

Scripts and tests stay exempt (same surface logic as ``bare-print``):
entry points and harnesses are exactly where pinning a knob is
legitimate — the operator IS the override path there.

Flagged write shapes (literal keys only — dynamic keys are plumbing, not
pins): ``os.environ["TIP_X"] = ...``, ``os.environ.setdefault("TIP_X",
...)`` and a literal ``"TIP_X"`` key inside ``os.environ.update({...})``.
``from os import environ`` aliases are resolved.
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.bare_print import _exempt


def _knob_envs() -> frozenset:
    """The planner-owned env vars (imported lazily: the registry lives in
    the analyzed package, and the analyzer must load even mid-refactor)."""
    try:
        from simple_tip_tpu.plan.knobs import planned_env_vars

        return planned_env_vars()
    except Exception:  # noqa: BLE001 — analyzer availability > one rule
        return frozenset()


def _knob_label(env: str) -> str:
    """``knob '<name>'`` for a registry env var (lazy, same caveat as
    :func:`_knob_envs`); falls back to the bare env var mid-refactor."""
    try:
        from simple_tip_tpu.plan.knobs import knob_for_env

        k = knob_for_env(env)
        return f"knob {k.name!r}" if k is not None else env
    except Exception:  # noqa: BLE001 — analyzer availability > one rule
        return env


def _environ_names(tree) -> set:
    """Local names bound to ``os.environ`` (``from os import environ [as e]``)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "os":
            for alias in node.names:
                if alias.name == "environ":
                    names.add(alias.asname or "environ")
    return names


def _is_environ(node, environ_names) -> bool:
    """Whether ``node`` is an expression resolving to ``os.environ``."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return isinstance(node.value, ast.Name) and node.value.id == "os"
    return isinstance(node, ast.Name) and node.id in environ_names


def _literal_knob(node, knob_envs):
    """The knob env name if ``node`` is a string constant in the registry."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value in knob_envs:
            return node.value
    return None


@register
class HardcodedKnobRule(Rule):
    """Flag library writes of planner-owned TIP_* knobs into os.environ."""

    name = "hardcoded-knob"
    description = (
        "library code writes a planner-owned TIP_* tuning knob into "
        "os.environ; knob assignments must flow through the plan/knobs "
        "registry (an ExecutionPlan or the operator's shell), not a "
        "code-level pin (scripts/tests exempt)"
    )
    tags = ('knobs', 'planner')
    rationale = (
        "A code-level pin silently overrides any active ExecutionPlan and is "
        "invisible to plan explain and the plan-vs-actual audit; knob values "
        "must come from the plan or the operator's shell."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Flag literal knob-env writes outside the exempt surfaces."""
        if _exempt(module):
            return
        knob_envs = _knob_envs()
        if not knob_envs:
            return
        environ_names = _environ_names(module.tree)

        def hit(lineno, env):
            return "", lineno, (
                f"{env} is a planner-owned tuning knob "
                f"({_knob_label(env)}, simple_tip_tpu/plan/knobs.py) "
                f"hardcoded into os.environ "
                f"here: the pin silently overrides any active ExecutionPlan "
                f"and is invisible to `plan explain` — take the value from "
                f"the plan (or let the operator's shell set it)"
            )

        for node in ast.walk(module.tree):
            # os.environ["TIP_X"] = ...
            if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Store
            ):
                env = _literal_knob(node.slice, knob_envs)
                if env and _is_environ(node.value, environ_names):
                    yield hit(node.lineno, env)
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                # os.environ.setdefault("TIP_X", ...)
                if (
                    node.func.attr == "setdefault"
                    and _is_environ(node.func.value, environ_names)
                    and node.args
                ):
                    env = _literal_knob(node.args[0], knob_envs)
                    if env:
                        yield hit(node.lineno, env)
                # os.environ.update({"TIP_X": ...})
                elif (
                    node.func.attr == "update"
                    and _is_environ(node.func.value, environ_names)
                ):
                    for arg in node.args:
                        if isinstance(arg, ast.Dict):
                            for key in arg.keys:
                                env = _literal_knob(key, knob_envs)
                                if env:
                                    yield hit(node.lineno, env)

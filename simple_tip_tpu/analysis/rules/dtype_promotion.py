"""Rule ``dtype-promotion``: unintended float64 widening in traced code.

The semantic sibling of the syntactic ``f64-on-tpu`` rule: instead of
pattern-matching ``np.float64`` literals, the tipcheck interpreter
(``analysis.shapes``) tracks dtypes through the jnp promotion lattice and
flags the *result* of a promotion landing in f64 inside traced code — the
``jnp.f32_array * np.linspace(...)`` case, where no f64 literal appears
anywhere but numpy's float64 default wins the promotion.

Scope is deliberately narrow to stay false-positive-free:

- only **rank >= 1** float64 results count (rank-0 scalars are weakly
  typed in JAX's x64-disabled default and canonicalize harmlessly),
- only when the operands were **not already all f64** (an all-f64
  pipeline is a deliberate choice, and ``f64-on-tpu`` covers the source),
- only inside **traced frames** (jit/vmap/shard_map bodies and their
  callees) — host-side f64 bookkeeping is fine,
- python scalar constants are weak types and never promote arrays.

TPUs have no f64 units; depending on x64 flags the result is either a
silent downcast (wrong precision expectations) or a slow emulation path.
"""

from typing import Iterator, Sequence, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register


@register
class DtypePromotionRule(Rule):
    """Flag inferred f64 promotions inside traced code."""

    name = "dtype-promotion"
    description = (
        "an operation inside traced code promotes mixed operands to a "
        "float64 array (TPUs have no f64 units)"
    )
    tags = ("tipcheck", "dtype", "semantic", "tpu")
    rationale = (
        "f64 rarely enters a TPU program through a literal; it enters "
        "through numpy defaults winning a promotion. Tracking dtypes "
        "through the promotion lattice catches the widening at the "
        "operation that commits it, not the symbol that seeded it."
    )

    def check_package(
        self, modules: Sequence[ModuleInfo]
    ) -> Iterator[Tuple[str, int, str]]:
        from simple_tip_tpu.analysis.shapes import project_shapes

        for f in project_shapes(modules).findings:
            if f.kind == self.name:
                yield f.module.path, f.line, f.message

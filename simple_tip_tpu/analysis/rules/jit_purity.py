"""Rule ``jit-purity``: side effects and concretization inside traced code.

A jitted function executes its Python body ONCE at trace time; ``print``,
global mutation and host-library calls silently run on the wrong schedule (or
not at all on cache hits), and ``.item()``/``float()``/``int()``/``bool()``
force a device→host sync that blocks the XLA pipeline mid-program. All of
these are trace-time bugs the runtime never reports.

Flags, inside jit-reachable functions (see ``common.jit_reachable_functions``):

- ``print(...)`` calls (use ``jax.debug.print`` while debugging — and remove
  it before shipping; leftover ``jax.debug.*`` is flagged too);
- ``global``/``nonlocal`` declarations (impure closure mutation);
- ``np.*``/``numpy.*``/``scipy.*`` calls (host library inside device code —
  breaks tracing or silently falls back to host);
- ``.item()`` calls and ``float()``/``int()``/``bool()`` casts on traced
  values (concretization; casts of ``.shape`` components are static and
  exempt);
- ``jax.debug.print``/``jax.debug.breakpoint`` leftovers.
"""

import ast
from typing import Iterator, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo, Rule, register
from simple_tip_tpu.analysis.rules.common import (
    callee_name,
    function_body_nodes,
    import_aliases,
    jit_reachable_functions,
)

_HOST_LIB_PREFIXES = ("numpy.", "scipy.")
_CAST_BUILTINS = {"float", "int", "bool", "complex"}


def _is_static_shape_expr(node: ast.AST) -> bool:
    """``int(x.shape[0])``-style casts are trace-time static, not syncs."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim", "size", "dtype"):
            return True
        if isinstance(sub, ast.Constant):
            return True
    return False


def iter_impurities(fn, aliases) -> Iterator[Tuple[int, str]]:
    """(line, message) for every impure/concretizing construct in ``fn``'s
    body, deduplicated by line. The building block shared by the local
    ``jit-purity`` rule and the call-graph-walking ``transitive-jit-purity``
    rule (rules/transitive_purity.py), which applies it to helpers reached
    from traced code in OTHER modules."""
    seen = set()
    for node in function_body_nodes(fn):
        for _rel, line, msg in _check_node(node, aliases):
            if line not in seen:
                seen.add(line)
                yield line, msg


@register
class JitPurityRule(Rule):
    """Flag impure / concretizing constructs inside traced functions."""

    name = "jit-purity"
    description = (
        "print, global/nonlocal mutation, numpy/scipy calls, "
        ".item()/float()/int()/bool() concretization and jax.debug leftovers "
        "inside jit/vmap/scan-traced functions"
    )
    tags = ('traced', 'correctness')
    rationale = (
        "Side effects run once at trace time (wrong schedule, gone on cache "
        "hits); concretization stalls the XLA pipeline mid-program."
    )

    def check_module(self, module: ModuleInfo) -> Iterator[Tuple[str, int, str]]:
        """Run the impurity checks over every jit-reachable function."""
        aliases = import_aliases(module.tree)
        reachable = jit_reachable_functions(module.tree, aliases)
        seen = set()
        for fn in reachable:
            for node in function_body_nodes(fn):
                for finding in _check_node(node, aliases):
                    key = finding[:2]
                    if key not in seen:
                        seen.add(key)
                        yield finding


def _check_node(node, aliases):
    rel = ""  # filled in by the driver (relpath comes from the module)
    if isinstance(node, (ast.Global, ast.Nonlocal)):
        kind = "global" if isinstance(node, ast.Global) else "nonlocal"
        yield rel, node.lineno, (
            f"`{kind} {', '.join(node.names)}` inside a traced function: "
            "closure mutation runs at trace time only"
        )
        return
    if not isinstance(node, ast.Call):
        return
    name = callee_name(node, aliases)
    if name == "print":
        yield rel, node.lineno, (
            "print() inside a traced function executes at trace time "
            "only; use jax.debug.print while debugging"
        )
    elif name is not None and name.startswith("jax.debug."):
        yield rel, node.lineno, (
            f"{name}() left in traced code: debug callbacks stall the "
            "device pipeline in production"
        )
    elif name is not None and name.startswith(_HOST_LIB_PREFIXES):
        # Host-library math over static shape metadata (np.sqrt(x.shape[-1])
        # and friends) happens once at trace time and is pure — exempt.
        if node.args and all(_is_static_shape_expr(a) for a in node.args):
            return
        yield rel, node.lineno, (
            f"host-library call {name}() inside a traced function: "
            "use jax.numpy, or move the call outside jit"
        )
    elif name in _CAST_BUILTINS:
        if node.args and not any(
            _is_static_shape_expr(a) for a in node.args
        ):
            yield rel, node.lineno, (
                f"{name}() on a traced value forces a device->host sync "
                "inside the program; keep it as a jax array"
            )
    elif isinstance(node.func, ast.Attribute) and node.func.attr == "item":
        yield rel, node.lineno, (
            ".item() inside a traced function concretizes a traced "
            "value; return the array and read it on host"
        )

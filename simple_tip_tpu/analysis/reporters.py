"""tiplint output formats: human text and machine JSON.

Both reporters consume the full finding list (suppressed findings included)
so suppression debt stays visible in every report.
"""

import json
from typing import Iterable, List

from simple_tip_tpu.analysis.core import Finding, unsuppressed


def text_report(findings: Iterable[Finding]) -> str:
    """One ``path:line: [rule] message`` line per finding plus a summary."""
    findings = list(findings)
    active = unsuppressed(findings)
    lines = [f.format() for f in findings]
    lines.append(
        f"tiplint: {len(active)} finding(s), "
        f"{len(findings) - len(active)} suppressed"
    )
    return "\n".join(lines)


def json_report(findings: Iterable[Finding]) -> str:
    """Stable JSON document: per-finding records plus summary counts."""
    findings = list(findings)
    active = unsuppressed(findings)
    doc = {
        "findings": [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "message": f.message,
                "suppressed": f.suppressed,
            }
            for f in findings
        ],
        "summary": {
            "total": len(findings),
            "unsuppressed": len(active),
            "suppressed": len(findings) - len(active),
        },
    }
    return json.dumps(doc, indent=2, sort_keys=True)


REPORTERS = {"text": text_report, "json": json_report}


def render(findings: List[Finding], fmt: str) -> str:
    """Dispatch to the named reporter (KeyError on unknown format)."""
    return REPORTERS[fmt](findings)

"""tiplint output formats: text, JSON, GitHub annotations and SARIF.

All reporters consume the full finding list (suppressed findings included)
so suppression debt stays visible in every report.
"""

import json
from typing import Iterable, List

from simple_tip_tpu.analysis.core import Finding, unsuppressed


def text_report(findings: Iterable[Finding]) -> str:
    """One ``path:line: [rule] message`` line per finding plus a summary."""
    findings = list(findings)
    active = unsuppressed(findings)
    lines = [f.format() for f in findings]
    lines.append(
        f"tiplint: {len(active)} finding(s), "
        f"{len(findings) - len(active)} suppressed"
    )
    return "\n".join(lines)


def json_report(findings: Iterable[Finding]) -> str:
    """Stable JSON document: per-finding records plus summary counts."""
    findings = list(findings)
    active = unsuppressed(findings)
    doc = {
        "findings": [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "message": f.message,
                "suppressed": f.suppressed,
            }
            for f in findings
        ],
        "summary": {
            "total": len(findings),
            "unsuppressed": len(active),
            "suppressed": len(findings) - len(active),
        },
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def _gh_escape(value: str, *, property: bool = False) -> str:
    """GitHub workflow-command escaping (the documented %/CR/LF set; property
    values additionally escape ``:`` and ``,``)."""
    value = value.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    if property:
        value = value.replace(":", "%3A").replace(",", "%2C")
    return value


def github_report(findings: Iterable[Finding]) -> str:
    """GitHub Actions workflow commands: one ``::error`` annotation per
    unsuppressed finding (renders inline on the PR diff), ``::notice`` for
    suppressed ones (debt stays visible without failing review), plus the
    same trailing summary line as the text reporter."""
    findings = list(findings)
    active = unsuppressed(findings)
    lines = []
    for f in findings:
        level = "error" if not f.suppressed else "notice"
        message = f.message + (" (suppressed)" if f.suppressed else "")
        lines.append(
            f"::{level} file={_gh_escape(f.path, property=True)},"
            f"line={f.line},title=tiplint {_gh_escape(f.rule, property=True)}"
            f"::{_gh_escape(message)}"
        )
    lines.append(
        f"tiplint: {len(active)} finding(s), "
        f"{len(findings) - len(active)} suppressed"
    )
    return "\n".join(lines)


#: Synthetic finding kinds the driver emits without a registered Rule.
_SYNTHETIC_RULES = {
    "parse-error": "the file could not be parsed; nothing else was checked",
    "unused-suppression": (
        "a tiplint disable comment matched no finding; delete the stale "
        "comment or fix the rule name"
    ),
}


def sarif_report(findings: Iterable[Finding]) -> str:
    """SARIF 2.1.0 document (GitHub code scanning ingests this via
    ``codeql-action/upload-sarif``, so findings land in the Security tab
    and annotate PRs natively). Suppressed findings are carried with a
    ``suppressions`` entry instead of being dropped — the same
    debt-stays-visible contract as every other reporter. In-source
    ``# tiplint: disable`` comments map to kind ``inSource``;
    baseline-accepted findings map to kind ``external`` with a
    justification, so code scanning shows them as suppressed rather than
    vanished. Output is deterministic for fixed input (sorted keys, no
    timestamps)."""
    from simple_tip_tpu.analysis.core import all_rules

    findings = list(findings)
    rule_ids = sorted(
        {f.rule for f in findings}
        | set(all_rules())
        | set(_SYNTHETIC_RULES)
    )
    descriptions = {
        name: rule.description for name, rule in all_rules().items()
    }
    descriptions.update(_SYNTHETIC_RULES)
    rules = [
        {
            "id": rid,
            "shortDescription": {"text": descriptions.get(rid, rid)},
        }
        for rid in rule_ids
    ]
    index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "note" if f.suppressed else "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": max(1, f.line)},
                    }
                }
            ],
        }
        if f.suppressed:
            if f.baselined:
                result["suppressions"] = [
                    {
                        "kind": "external",
                        "justification": (
                            "accepted in tiplint_baseline.json (pre-"
                            "existing debt; new occurrences still fail)"
                        ),
                    }
                ]
            else:
                result["suppressions"] = [{"kind": "inSource"}]
        results.append(result)
    doc = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
            "Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "tiplint",
                        "informationUri": (
                            "https://github.com/simple-tip-tpu/simple-tip-tpu"
                        ),
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


REPORTERS = {
    "text": text_report,
    "json": json_report,
    "github": github_report,
    "sarif": sarif_report,
}


def render(findings: List[Finding], fmt: str) -> str:
    """Dispatch to the named reporter (KeyError on unknown format)."""
    return REPORTERS[fmt](findings)

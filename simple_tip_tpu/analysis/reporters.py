"""tiplint output formats: human text, machine JSON and GitHub annotations.

All reporters consume the full finding list (suppressed findings included)
so suppression debt stays visible in every report.
"""

import json
from typing import Iterable, List

from simple_tip_tpu.analysis.core import Finding, unsuppressed


def text_report(findings: Iterable[Finding]) -> str:
    """One ``path:line: [rule] message`` line per finding plus a summary."""
    findings = list(findings)
    active = unsuppressed(findings)
    lines = [f.format() for f in findings]
    lines.append(
        f"tiplint: {len(active)} finding(s), "
        f"{len(findings) - len(active)} suppressed"
    )
    return "\n".join(lines)


def json_report(findings: Iterable[Finding]) -> str:
    """Stable JSON document: per-finding records plus summary counts."""
    findings = list(findings)
    active = unsuppressed(findings)
    doc = {
        "findings": [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "message": f.message,
                "suppressed": f.suppressed,
            }
            for f in findings
        ],
        "summary": {
            "total": len(findings),
            "unsuppressed": len(active),
            "suppressed": len(findings) - len(active),
        },
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def _gh_escape(value: str, *, property: bool = False) -> str:
    """GitHub workflow-command escaping (the documented %/CR/LF set; property
    values additionally escape ``:`` and ``,``)."""
    value = value.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    if property:
        value = value.replace(":", "%3A").replace(",", "%2C")
    return value


def github_report(findings: Iterable[Finding]) -> str:
    """GitHub Actions workflow commands: one ``::error`` annotation per
    unsuppressed finding (renders inline on the PR diff), ``::notice`` for
    suppressed ones (debt stays visible without failing review), plus the
    same trailing summary line as the text reporter."""
    findings = list(findings)
    active = unsuppressed(findings)
    lines = []
    for f in findings:
        level = "error" if not f.suppressed else "notice"
        message = f.message + (" (suppressed)" if f.suppressed else "")
        lines.append(
            f"::{level} file={_gh_escape(f.path, property=True)},"
            f"line={f.line},title=tiplint {_gh_escape(f.rule, property=True)}"
            f"::{_gh_escape(message)}"
        )
    lines.append(
        f"tiplint: {len(active)} finding(s), "
        f"{len(findings) - len(active)} suppressed"
    )
    return "\n".join(lines)


REPORTERS = {"text": text_report, "json": json_report, "github": github_report}


def render(findings: List[Finding], fmt: str) -> str:
    """Dispatch to the named reporter (KeyError on unknown format)."""
    return REPORTERS[fmt](findings)

"""tipcheck: abstract interpretation of shapes, dtypes and sharding.

The project-graph rules (PR 2) see *names* — a PartitionSpec axis that no
mesh declares — and the dataflow rules (PR 16) see *facts* — a donated
buffer read after donation. Neither can answer the questions that actually
sink a sharded program on a real pod slice: does this dim **divide** by the
mesh axis it is sharded over, is this reshape element-count-preserving
under the shapes that reach it, does dtype promotion silently widen to f64
inside traced code? This module answers them with a conservative abstract
interpreter over the same stdlib-``ast`` trees:

- an abstract array is ``Arr(dims, dtype, spec, chain)`` where each dim is
  a concrete ``int``, an interned symbol (``Sym('B')`` — from the declared
  contract table), or ``DYN`` (statically unknown); ``chain`` is the
  provenance trail rendered into findings like the dataflow taint chains;
- transfer functions cover the jnp/np/lax/nn vocabulary the package uses
  (matmul/einsum, reshape/transpose/concat/stack/pad, reductions,
  broadcasting + dtype promotion, conv/pool for the MNIST/CIFAR kernels)
  plus the transform boundaries: ``vmap`` prepends the mapped dim,
  ``shard_map`` divides spec'd dims by the mesh axis size, ``jit``
  in_shardings attach and are divisibility-checked;
- whole-program entry points are (a) every module's top-level statement
  list, (b) every traced function the project graph discovers, (c) the
  declared-contract table below (entry shapes seeded from the CaseStudy
  registry — badge size 128, 10 classes — and the attention helpers'
  documented ``[B, T, H, D]`` layout), interpreted interprocedurally
  through resolvable project calls.

Everything degrades to ``DYN``/``UNKNOWN`` rather than guessing: a mesh
built from ``jax.devices()`` or ``jax.device_count()`` has ``DYN`` axis
sizes and can never produce a divisibility finding; an unresolvable call
returns ``UNKNOWN`` and downstream checks go silent. Findings are deduped
per (kind, module, line) and fully deterministic, so ``--cache`` replay
stays byte-identical.

Like every analysis module this is pure stdlib — no jax import, ever.
"""

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo
from simple_tip_tpu.analysis.graph import (
    MESH_CALLEES,
    PARTITION_SPEC_CALLEES,
    FunctionInfo,
    project_graph,
)
from simple_tip_tpu.analysis.rules.common import callee_name, dotted

# --------------------------------------------------------------------------
# value model
# --------------------------------------------------------------------------


class _DynType:
    """Statically-unknown dimension (prints as ``?``)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "?"


DYN = _DynType()


class Sym:
    """An interned symbolic dimension (``Sym('B')`` from a contract)."""

    _interned: Dict[str, "Sym"] = {}
    __slots__ = ("name",)

    def __new__(cls, name: str):
        sym = cls._interned.get(name)
        if sym is None:
            sym = super().__new__(cls)
            sym.name = name
            cls._interned[name] = sym
        return sym

    def __repr__(self):
        return self.name


class _UnknownType:
    """Top of the value lattice: no information."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<unknown>"


UNKNOWN = _UnknownType()

#: provenance chain entry list: ((line, description), ...), capped at 6
Chain = Tuple[Tuple[int, str], ...]


@dataclass(eq=False)
class Arr:
    """Abstract array: dims (None = unknown rank), dtype, sharding spec."""

    dims: Optional[Tuple[object, ...]]
    dtype: Optional[str] = None
    spec: Optional[Tuple[object, ...]] = None  # PartitionSpec entries
    chain: Chain = ()


@dataclass(eq=False)
class Const:
    """A concrete python value (int, float, str, bool, None, Ellipsis)."""

    value: object


@dataclass(eq=False)
class TupVal:
    """A tuple/list of abstract values."""

    items: Tuple[object, ...]


@dataclass(eq=False)
class MeshVal:
    """A device mesh: axis names plus per-axis sizes (int or DYN)."""

    axes: Tuple[str, ...]
    sizes: Tuple[object, ...]


@dataclass(eq=False)
class MeshShapeVal:
    """``mesh.shape`` — an axis-name -> size mapping view."""

    mesh: MeshVal


@dataclass(eq=False)
class SpecVal:
    """A PartitionSpec: positional entries (str axis | tuple | None | DYN)."""

    entries: Tuple[object, ...]


@dataclass(eq=False)
class ShardingVal:
    """A NamedSharding: mesh + spec (either side may be unknown)."""

    mesh: Optional[MeshVal]
    spec: Optional[SpecVal]


@dataclass(eq=False)
class DtypeVal:
    """A dtype object (``jnp.float32``); calling it casts."""

    name: str


@dataclass(eq=False)
class FnVal:
    """A callable: project function, nested def/lambda, or builtin name.

    ``kw_unknown`` marks a partial application whose keyword bindings did
    not resolve — unbound parameters become UNKNOWN instead of taking
    their defaults (the conservative reading of ``partial(f, **kw)``).
    """

    module: Optional[ModuleInfo] = None
    node: Optional[ast.AST] = None  # FunctionDef/Lambda for project code
    closure: Optional[dict] = None  # enclosing env for nested defs/lambdas
    builtin: Optional[str] = None  # canonical dotted name otherwise
    bound_args: Tuple = ()
    bound_kwargs: Optional[dict] = None
    kw_unknown: bool = False


@dataclass(eq=False)
class XformVal:
    """A transform-wrapped callable (jit/vmap/pmap/grad/shard_map/...)."""

    kind: str
    fn: object
    meta: dict


@dataclass(eq=False)
class LayerVal:
    """A constructed flax layer (Conv/Dense/pool config), callable."""

    kind: str
    meta: dict


@dataclass(eq=False)
class MethodVal:
    """A bound method reference (``x.reshape``), dispatched at call."""

    obj: object
    attr: str


@dataclass(eq=False)
class AtIdxVal:
    """``x.at[idx]`` — ``.set``/``.add``/... return the base array."""

    arr: Arr


@dataclass(eq=False)
class ModRef:
    """A dotted module/prefix reference (``jax.sharding``)."""

    name: str


@dataclass(eq=False)
class ShapeFinding:
    """One interpreter finding, consumed by the thin rule wrappers."""

    kind: str  # shape-mismatch | indivisible-sharding | dtype-promotion | vmap-axis-clash
    module: ModuleInfo
    line: int
    message: str


class _Budget(Exception):
    """Raised internally when the per-run interpretation budget runs out."""


# --------------------------------------------------------------------------
# dtypes and formatting
# --------------------------------------------------------------------------

_DTYPE_NAMES = {
    "bool", "int8", "uint8", "int16", "uint16", "int32", "uint32",
    "int64", "uint64", "bfloat16", "float16", "float32", "float64",
    "complex64", "complex128",
}

_DTYPE_SHORT = {
    "bool": "bool", "int8": "i8", "uint8": "u8", "int16": "i16",
    "uint16": "u16", "int32": "i32", "uint32": "u32", "int64": "i64",
    "uint64": "u64", "bfloat16": "bf16", "float16": "f16",
    "float32": "f32", "float64": "f64", "complex64": "c64",
    "complex128": "c128",
}

_PROMO_ORDER = {
    "bool": 0, "int8": 1, "uint8": 1, "int16": 2, "uint16": 2,
    "int32": 3, "uint32": 3, "int64": 4, "uint64": 4,
    "bfloat16": 5, "float16": 5, "float32": 6, "float64": 7,
    "complex64": 8, "complex128": 9,
}


def promote_dtype(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """JAX-style strong-type promotion; None (unknown) is absorbing."""
    if a is None or b is None:
        return None
    if a == b:
        return a
    oa, ob = _PROMO_ORDER.get(a), _PROMO_ORDER.get(b)
    if oa is None or ob is None:
        return None
    if oa == ob:
        # bfloat16 x float16 promotes to float32 in JAX's lattice
        return "float32" if oa == 5 else a
    return a if oa > ob else b


def fmt_dims(dims: Optional[Tuple[object, ...]]) -> str:
    """``[4,128,?,B]`` — ``[...]`` when the rank itself is unknown."""
    if dims is None:
        return "[...]"
    return "[" + ",".join(repr(d) for d in dims) + "]"


def fmt_arr(arr: Arr) -> str:
    """``f32[4,128,8,64]`` (``arr`` when the dtype is unknown)."""
    short = _DTYPE_SHORT.get(arr.dtype or "", arr.dtype or "arr")
    return f"{short}{fmt_dims(arr.dims)}"


def fmt_spec(entries: Tuple[object, ...]) -> str:
    """``P(None, 'sp', None)`` — the PartitionSpec literal rendering."""
    parts = []
    for e in entries:
        if e is None:
            parts.append("None")
        elif isinstance(e, tuple):
            parts.append("(" + ", ".join(repr(x) for x in e) + ")")
        elif e is DYN:
            parts.append("?")
        else:
            parts.append(repr(e))
    return "P(" + ", ".join(parts) + ")"


def extend_chain(chain: Chain, line: int, desc: str) -> Chain:
    """Append a provenance hop, keeping the source plus the last 5 hops."""
    new = tuple(chain) + ((line, desc),)
    if len(new) > 6:
        new = new[:1] + new[-5:]
    return new


def render_chain(chain: Chain) -> str:
    """The dataflow-style ``desc (line N) -> ...`` provenance rendering."""
    return " -> ".join(f"{desc} (line {line})" for line, desc in chain)


def _dim_to_val(dim: object) -> object:
    """A dim as a scalar abstract value (for ``x.shape`` unpacking)."""
    if isinstance(dim, int):
        return Const(dim)
    if isinstance(dim, Sym):
        return dim
    return UNKNOWN


def _val_to_dim(val: object) -> object:
    """A scalar abstract value as a dim (for ``reshape(b, -1, 32)``)."""
    if isinstance(val, Const) and isinstance(val.value, int) and not isinstance(val.value, bool):
        return val.value
    if isinstance(val, Sym):
        return val
    return DYN


def _known_int(val: object) -> Optional[int]:
    if isinstance(val, Const) and isinstance(val.value, int) and not isinstance(val.value, bool):
        return val.value
    return None


def _truthiness(val: object) -> Optional[bool]:
    """Definite truth value, or None when statically unknown."""
    if isinstance(val, Const):
        try:
            return bool(val.value)
        except Exception:
            return None
    if isinstance(val, TupVal):
        return bool(val.items)
    return None


#: transform-wrapper callees -> interpreter kind
_XFORM_KINDS = {
    "jax.jit": "jit",
    "jax.pjit": "jit",
    "jax.experimental.pjit.pjit": "jit",
    "jax.checkpoint": "jit",
    "jax.remat": "jit",
    "jax.named_call": "jit",
    "jax.vmap": "vmap",
    "jax.pmap": "pmap",
    "jax.grad": "grad",
    "jax.value_and_grad": "value_and_grad",
    "jax.shard_map": "shard_map",
    "jax.experimental.shard_map.shard_map": "shard_map",
    "jax.experimental.pallas.pallas_call": "pallas_call",
}

#: decorators that wrap without changing the callable's abstract behavior
_PASSTHROUGH_DECORATORS = {
    "functools.lru_cache", "functools.cache", "functools.wraps",
    "staticmethod", "classmethod", "property", "typing.overload",
    "abc.abstractmethod", "nn.compact", "flax.linen.compact",
}

#: attribute constants (``jnp.inf`` and friends)
_ATTR_CONSTS = {}
for _mod in ("jax.numpy", "numpy", "math"):
    _ATTR_CONSTS[f"{_mod}.inf"] = float("inf")
    _ATTR_CONSTS[f"{_mod}.nan"] = float("nan")
    _ATTR_CONSTS[f"{_mod}.pi"] = 3.141592653589793
    _ATTR_CONSTS[f"{_mod}.e"] = 2.718281828459045
_ATTR_CONSTS["numpy.newaxis"] = None
_ATTR_CONSTS["jax.numpy.newaxis"] = None

_NAMED_SHARDING_CALLEES = {
    "jax.sharding.NamedSharding",
    "jax.NamedSharding",
}

#: elementwise unary array functions (shape- and mostly dtype-preserving)
_UNARY_ELEMENTWISE = {
    "exp", "exp2", "expm1", "log", "log2", "log10", "log1p", "sqrt",
    "cbrt", "sin", "cos", "tan", "arcsin", "arccos", "arctan", "sinh",
    "cosh", "tanh", "arcsinh", "arccosh", "arctanh", "abs", "absolute",
    "fabs", "negative", "positive", "sign", "floor", "ceil", "rint",
    "trunc", "square", "reciprocal", "conjugate", "conj", "real", "imag",
    "nan_to_num", "degrees", "radians", "rad2deg", "deg2rad", "i0",
    "sinc", "erf",
}

#: unary functions that always return bool arrays
_UNARY_BOOL = {"isnan", "isinf", "isfinite", "isneginf", "isposinf",
               "logical_not", "signbit"}

#: unary float-promoting set (int input becomes the lib's default float)
_UNARY_FLOATING = {
    "exp", "exp2", "expm1", "log", "log2", "log10", "log1p", "sqrt",
    "cbrt", "sin", "cos", "tan", "arcsin", "arccos", "arctan", "sinh",
    "cosh", "tanh", "arcsinh", "arccosh", "arctanh", "reciprocal",
    "degrees", "radians", "rad2deg", "deg2rad", "sinc", "erf",
}

#: binary broadcasting array functions
_BINARY_BROADCAST = {
    "add", "subtract", "multiply", "divide", "true_divide",
    "floor_divide", "mod", "remainder", "fmod", "power", "float_power",
    "maximum", "minimum", "fmax", "fmin", "hypot", "arctan2",
    "logaddexp", "logaddexp2", "nextafter", "copysign", "heaviside",
    "left_shift", "right_shift", "bitwise_and", "bitwise_or",
    "bitwise_xor", "gcd", "lcm",
}

#: binary broadcasting comparisons (bool result)
_BINARY_BOOL = {
    "equal", "not_equal", "greater", "less", "greater_equal",
    "less_equal", "logical_and", "logical_or", "logical_xor",
    "isclose", "array_equal",
}

#: axis reductions
_REDUCTIONS = {
    "sum", "mean", "prod", "max", "min", "amax", "amin", "nanmax",
    "nanmin", "nansum", "nanmean", "var", "std", "nanvar", "nanstd",
    "all", "any", "median", "nanmedian", "count_nonzero", "ptp",
    "argmax", "argmin", "nanargmax", "nanargmin", "logsumexp",
}

_REDUCTION_INT_RESULT = {"argmax", "argmin", "nanargmax", "nanargmin",
                         "count_nonzero"}
_REDUCTION_BOOL_RESULT = {"all", "any"}

#: shape-preserving array transforms
_SAME_SHAPE = {
    "sort", "argsort", "flip", "fliplr", "flipud", "roll", "clip",
    "cumsum", "cumprod", "nancumsum", "nancumprod", "tril", "triu",
    "round", "around", "copy", "asarray_chkfinite", "ascontiguousarray",
    "stop_gradient",
}

#: jax.nn elementwise activations (shape-preserving, float-promoting)
_NN_UNARY = {
    "relu", "relu6", "gelu", "silu", "swish", "sigmoid", "tanh",
    "softplus", "soft_sign", "log_sigmoid", "elu", "leaky_relu", "selu",
    "celu", "hard_sigmoid", "hard_silu", "hard_swish", "hard_tanh",
    "softmax", "log_softmax", "standardize", "normalize", "squareplus",
    "mish", "logsumexp",
}


# --------------------------------------------------------------------------
# declared entry contracts
# --------------------------------------------------------------------------


def _bthd(dtype: Optional[str] = None) -> Arr:
    return Arr((Sym("B"), Sym("T"), Sym("H"), Sym("D")), dtype)


#: CaseStudy registry constants the contract table is seeded from
#: (casestudies/mini.py: prediction_badge_size=128, num_classes=10).
BADGE_SIZE = 128
NUM_CLASSES = 10

#: dotted function name -> {param name: abstract value}. Entry shapes for
#: interprocedural verification of whole chains; params not named here
#: bind UNKNOWN. Layouts come from each function's documented contract.
CONTRACTS: Dict[str, Dict[str, object]] = {
    # sequence-parallel attention: per-device [batch, seq, heads, head_dim]
    "simple_tip_tpu.parallel.ring_attention.ring_attention": {
        "q": _bthd(), "k": _bthd(), "v": _bthd(),
    },
    "simple_tip_tpu.parallel.ring_attention.dense_attention_f32_softmax": {
        "q": _bthd(), "k": _bthd(), "v": _bthd(),
    },
    "simple_tip_tpu.parallel.ring_attention.ring_self_attention_reference": {
        "q": _bthd(), "k": _bthd(), "v": _bthd(),
    },
    "simple_tip_tpu.parallel.ulysses_attention.ulysses_attention": {
        "q": _bthd(), "k": _bthd(), "v": _bthd(),
    },
    # fused chain: badge-sized traced vectors (badge rows x flattened bits)
    "simple_tip_tpu.ops.fused_chain.pack_bits_u32": {
        "flat": Arr((BADGE_SIZE, Sym("W")), "bool"),
    },
    "simple_tip_tpu.ops.fused_chain.select_top_k": {
        "values": Arr((Sym("N"),), "float32"),
        "valid": Arr((), "int32"),
    },
    # convnet entries: NHWC badge batches, 10-class head
    "simple_tip_tpu.models.convnet.MnistConvNet.__call__": {
        "x": Arr((Sym("B"), 28, 28, 1), "float32"),
    },
    "simple_tip_tpu.models.convnet.Cifar10ConvNet.__call__": {
        "x": Arr((Sym("B"), 32, 32, 3), "float32"),
    },
}


# --------------------------------------------------------------------------
# the interpreter
# --------------------------------------------------------------------------


@dataclass(eq=False)
class _Frame:
    """One interpretation frame (module scope or function activation)."""

    module: ModuleInfo
    env: Dict[str, object]
    traced: bool
    axis_env: Dict[str, object]  # mesh axis name -> size (int | DYN)
    depth: int
    stack: frozenset  # ids of function nodes on the interpretive call stack
    returns: List[object] = field(default_factory=list)


_MAX_DEPTH = 8
_STEP_BUDGET = 400_000


class ProjectShapes:
    """Whole-program shape/dtype/sharding interpretation of one module set.

    Build once per run via :func:`project_shapes` (identity-cached on the
    module list like ``project_graph``); the four shape rules are thin
    filters over :attr:`findings`.
    """

    def __init__(self, modules: Sequence[ModuleInfo]):
        self.modules = modules
        self.graph = project_graph(modules)
        self.findings: List[ShapeFinding] = []
        self._seen: Set[Tuple[str, int, int]] = set()
        self._module_env: Dict[int, Dict[str, object]] = {}
        self._by_name: Dict[str, ModuleInfo] = {
            self.graph.module_name(m): m for m in modules
        }
        self._steps = _STEP_BUDGET
        self._debug = bool(os.environ.get("TIPLINT_SHAPES_DEBUG"))
        self._run()

    # -- driver ------------------------------------------------------------

    def _run(self) -> None:
        for m in self.modules:
            self._env_of(m)
        ran: Set[int] = set()
        for fi, _boundary in self.graph.traced_entries():
            ran.add(id(fi))
            self._run_entry(fi)
        for name in sorted(CONTRACTS):
            fi = self.graph.functions.get(name)
            if fi is not None and id(fi) not in ran:
                ran.add(id(fi))
                self._run_entry(fi)
        # Fallback sweep: every remaining function runs untraced with
        # UNKNOWN parameters, so locally-constructed shapes (vmap calls,
        # mesh/device_put sites, concatenations) are still checked even
        # when nothing jit-reachable calls them.
        for name in sorted(self.graph.functions):
            fi = self.graph.functions[name]
            if id(fi) not in ran:
                ran.add(id(fi))
                self._run_entry(fi, traced=False)

    def _guard(self, fn, *args):
        """Run one entry; interpreter errors never break the analyzer."""
        try:
            return fn(*args)
        except _Budget:
            return None
        except RecursionError:
            return None
        except Exception:
            if self._debug:
                raise
            return None

    def _env_of(self, module: ModuleInfo) -> Dict[str, object]:
        """The module's interpreted top-level environment (memoized)."""
        key = id(module)
        if key in self._module_env:
            return self._module_env[key]
        env: Dict[str, object] = {}
        self._module_env[key] = env
        frame = _Frame(module=module, env=env, traced=False, axis_env={},
                       depth=0, stack=frozenset())
        self._guard(self._exec_block, frame, module.tree.body)
        return env

    def _run_entry(self, fi: FunctionInfo, traced: bool = True) -> None:
        """Interpret one traced/contracted function standalone."""
        contract = CONTRACTS.get(fi.dotted, {})
        self._guard(self._entry_body, fi, contract, traced)

    def _entry_body(self, fi: FunctionInfo, contract: Dict[str, object],
                    traced: bool = True):
        frame = _Frame(module=fi.module, env=dict(self._env_of(fi.module)),
                       traced=traced, axis_env={}, depth=0, stack=frozenset())
        self._call_project(fi.module, fi.node, None, [], dict(contract),
                           frame, fi.node.lineno, kw_unknown=False,
                           contract_defaults=True)

    # -- findings ----------------------------------------------------------

    def _emit(self, kind: str, frame: _Frame, line: int, message: str,
              chain: Chain = ()) -> None:
        key = (kind, id(frame.module), line)
        if key in self._seen:
            return
        self._seen.add(key)
        if chain:
            message = f"{message}; inferred: {render_chain(chain)}"
        self.findings.append(
            ShapeFinding(kind=kind, module=frame.module, line=line,
                         message=message)
        )

    # -- statements --------------------------------------------------------

    def _step(self) -> None:
        self._steps -= 1
        if self._steps <= 0:
            raise _Budget()

    def _exec_block(self, frame: _Frame, stmts: Sequence[ast.stmt]) -> str:
        """Execute statements; returns 'dead' when control definitely left
        the block (return/raise/break/continue), else 'live'."""
        for stmt in stmts:
            status = self._exec_stmt(frame, stmt)
            if status == "dead":
                return "dead"
        return "live"

    def _exec_stmt(self, frame: _Frame, stmt: ast.stmt) -> str:
        self._step()
        try:
            return self._exec_stmt_inner(frame, stmt)
        except _Budget:
            raise
        except RecursionError:
            raise
        except Exception:
            if self._debug:
                raise
            return "live"

    def _exec_stmt_inner(self, frame: _Frame, stmt: ast.stmt) -> str:
        if isinstance(stmt, ast.Expr):
            self._eval(frame, stmt.value)
            return "live"
        if isinstance(stmt, ast.Assign):
            val = self._eval(frame, stmt.value)
            for target in stmt.targets:
                self._assign(frame, target, val)
            return "live"
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign(frame, stmt.target,
                             self._eval(frame, stmt.value))
            return "live"
        if isinstance(stmt, ast.AugAssign):
            cur = self._eval(frame, stmt.target)
            rhs = self._eval(frame, stmt.value)
            val = self._binop(frame, stmt.op, cur, rhs, stmt.lineno)
            self._assign(frame, stmt.target, val)
            return "live"
        if isinstance(stmt, ast.Return):
            frame.returns.append(
                Const(None) if stmt.value is None
                else self._eval(frame, stmt.value)
            )
            return "dead"
        if isinstance(stmt, ast.If):
            return self._exec_if(frame, stmt)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._exec_for(frame, stmt)
        if isinstance(stmt, ast.While):
            return self._exec_while(frame, stmt)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            frame.env[stmt.name] = self._bind_def(frame, stmt)
            return "live"
        if isinstance(stmt, ast.Lambda):  # pragma: no cover - not a stmt
            return "live"
        if isinstance(stmt, ast.ClassDef):
            frame.env[stmt.name] = UNKNOWN
            return "live"
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._eval(frame, stmt.exc)
            return "dead"
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return "dead"
        if isinstance(stmt, ast.Assert):
            self._eval(frame, stmt.test)
            return "live"
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            # Name resolution falls back to the graph's alias table, which
            # already indexes imports anywhere in the file.
            return "live"
        if isinstance(stmt, ast.Try):
            pre = dict(frame.env)
            body_status = self._exec_block(frame, stmt.body)
            envs = [frame.env] if body_status == "live" else []
            for handler in stmt.handlers:
                henv = dict(pre)
                hframe = self._fork(frame, henv)
                if handler.name:
                    henv[handler.name] = UNKNOWN
                if self._exec_block(hframe, handler.body) == "live":
                    envs.append(henv)
            if stmt.orelse and body_status == "live":
                if self._exec_block(frame, stmt.orelse) == "dead":
                    envs = [e for e in envs if e is not frame.env]
            frame.env.clear()
            frame.env.update(self._join_envs(envs) if envs else pre)
            if stmt.finalbody:
                self._exec_block(frame, stmt.finalbody)
            return "live" if envs else "dead"
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                val = self._eval(frame, item.context_expr)
                if item.optional_vars is not None:
                    self._assign(frame, item.optional_vars, val)
            return self._exec_block(frame, stmt.body)
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    frame.env.pop(target.id, None)
            return "live"
        # Global/Nonlocal/Pass/Match and anything newer: no env effect we
        # can model soundly — weaken every name the statement assigns.
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                frame.env[node.id] = UNKNOWN
        return "live"

    def _fork(self, frame: _Frame, env: Dict[str, object]) -> _Frame:
        new = _Frame(module=frame.module, env=env, traced=frame.traced,
                     axis_env=frame.axis_env, depth=frame.depth,
                     stack=frame.stack)
        new.returns = frame.returns  # share the return accumulator
        return new

    def _exec_if(self, frame: _Frame, stmt: ast.If) -> str:
        cond = self._eval(frame, stmt.test)
        truth = _truthiness(cond)
        if truth is True:
            return self._exec_block(frame, stmt.body)
        if truth is False:
            return self._exec_block(frame, stmt.orelse)
        then_env = dict(frame.env)
        else_env = dict(frame.env)
        then_status = self._exec_block(self._fork(frame, then_env), stmt.body)
        else_status = self._exec_block(self._fork(frame, else_env), stmt.orelse)
        live = [env for env, status in ((then_env, then_status),
                                        (else_env, else_status))
                if status == "live"]
        if not live:
            return "dead"
        frame.env.clear()
        frame.env.update(self._join_envs(live))
        return "live"

    def _exec_for(self, frame: _Frame, stmt) -> str:
        iterable = self._eval(frame, stmt.iter)
        pre = dict(frame.env)
        item: object = UNKNOWN
        if isinstance(iterable, TupVal) and iterable.items:
            item = iterable.items[0]
            for other in iterable.items[1:]:
                item = self._join(item, other)
        self._assign(frame, stmt.target, item)
        self._exec_block(frame, stmt.body)
        if stmt.orelse:
            self._exec_block(frame, stmt.orelse)
        joined = self._join_envs([pre, dict(frame.env)])
        frame.env.clear()
        frame.env.update(joined)
        return "live"

    def _exec_while(self, frame: _Frame, stmt: ast.While) -> str:
        self._eval(frame, stmt.test)
        pre = dict(frame.env)
        self._exec_block(frame, stmt.body)
        if stmt.orelse:
            self._exec_block(frame, stmt.orelse)
        joined = self._join_envs([pre, dict(frame.env)])
        frame.env.clear()
        frame.env.update(joined)
        return "live"

    def _bind_def(self, frame: _Frame, stmt) -> object:
        """A def statement's bound value: FnVal wrapped by its decorators."""
        val: object = FnVal(module=frame.module, node=stmt,
                            closure=frame.env)
        aliases = self.graph.aliases(frame.module)
        for deco in reversed(stmt.decorator_list):
            name = dotted(deco, aliases)
            if name in _PASSTHROUGH_DECORATORS:
                continue
            if name in _XFORM_KINDS:
                val = XformVal(kind=_XFORM_KINDS[name], fn=val, meta={})
                continue
            if isinstance(deco, ast.Call):
                inner = callee_name(deco, aliases)
                if inner in _PASSTHROUGH_DECORATORS:
                    continue
                if inner in _XFORM_KINDS:
                    meta = self._eval_kwargs(frame, deco)[0]
                    val = XformVal(kind=_XFORM_KINDS[inner], fn=val, meta=meta)
                    continue
                if inner in ("functools.partial", "partial") and deco.args:
                    first = dotted(deco.args[0], aliases)
                    if first in _XFORM_KINDS:
                        meta = self._eval_kwargs(frame, deco)[0]
                        val = XformVal(kind=_XFORM_KINDS[first], fn=val,
                                       meta=meta)
                        continue
            return UNKNOWN  # unmodeled decorator: value unknown
        return val

    def _eval_kwargs(self, frame: _Frame, call: ast.Call):
        """(kwargs dict, kw_splat flag) for a call's keyword arguments."""
        kwargs: Dict[str, object] = {}
        splat = False
        for kw in call.keywords:
            if kw.arg is None:
                splat = True
                self._eval(frame, kw.value)
            else:
                kwargs[kw.arg] = self._eval(frame, kw.value)
        return kwargs, splat

    def _assign(self, frame: _Frame, target: ast.expr, val: object) -> None:
        if isinstance(target, ast.Name):
            frame.env[target.id] = val
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            items: Optional[Tuple[object, ...]] = None
            if isinstance(val, TupVal):
                items = val.items
            elif isinstance(val, Arr) and val.dims is not None and val.dims:
                lead = val.dims[0]
                if isinstance(lead, int) and lead == len(target.elts):
                    items = tuple(
                        Arr(val.dims[1:], val.dtype) for _ in target.elts
                    )
            has_star = any(isinstance(e, ast.Starred) for e in target.elts)
            if items is not None and not has_star and \
                    len(items) == len(target.elts):
                for sub, item in zip(target.elts, items):
                    self._assign(frame, sub, item)
                return
            for sub in target.elts:
                inner = sub.value if isinstance(sub, ast.Starred) else sub
                self._assign(frame, inner, UNKNOWN)
            return
        # Subscript/Attribute stores: no model (objects are opaque here).

    # -- joins -------------------------------------------------------------

    def _join_envs(self, envs: List[Dict[str, object]]) -> Dict[str, object]:
        if len(envs) == 1:
            return envs[0]
        keys = set()
        for env in envs:
            keys.update(env)
        out: Dict[str, object] = {}
        for key in keys:
            if not all(key in env for env in envs):
                out[key] = UNKNOWN
                continue
            val = envs[0][key]
            for env in envs[1:]:
                val = self._join(val, env[key])
            out[key] = val
        return out

    def _join(self, a: object, b: object) -> object:
        if a is b:
            return a
        if isinstance(a, Arr) and isinstance(b, Arr):
            dims: Optional[Tuple[object, ...]]
            if a.dims is None or b.dims is None or len(a.dims) != len(b.dims):
                dims = None
            else:
                dims = tuple(
                    da if (da is db or (isinstance(da, int) and da == db))
                    else DYN
                    for da, db in zip(a.dims, b.dims)
                )
            dtype = a.dtype if a.dtype == b.dtype else None
            spec = a.spec if a.spec == b.spec else None
            return Arr(dims, dtype, spec, a.chain or b.chain)
        if isinstance(a, Const) and isinstance(b, Const):
            try:
                if type(a.value) is type(b.value) and a.value == b.value:
                    return a
            except Exception:
                pass
            return UNKNOWN
        if isinstance(a, TupVal) and isinstance(b, TupVal):
            if len(a.items) == len(b.items):
                return TupVal(tuple(
                    self._join(x, y) for x, y in zip(a.items, b.items)
                ))
            return UNKNOWN
        if isinstance(a, FnVal) and isinstance(b, FnVal):
            if a.node is b.node and a.builtin == b.builtin:
                merged = FnVal(
                    module=a.module, node=a.node, closure=a.closure,
                    builtin=a.builtin, bound_args=a.bound_args,
                    bound_kwargs=a.bound_kwargs,
                    kw_unknown=a.kw_unknown or b.kw_unknown
                    or a.bound_kwargs != b.bound_kwargs
                    or len(a.bound_args) != len(b.bound_args),
                )
                return merged
            return UNKNOWN
        if isinstance(a, MeshVal) and isinstance(b, MeshVal):
            if a.axes == b.axes and a.sizes == b.sizes:
                return a
            return UNKNOWN
        if isinstance(a, SpecVal) and isinstance(b, SpecVal):
            if a.entries == b.entries:
                return a
            return UNKNOWN
        if isinstance(a, DtypeVal) and isinstance(b, DtypeVal):
            return a if a.name == b.name else UNKNOWN
        return UNKNOWN

    # -- expressions -------------------------------------------------------

    def _eval(self, frame: _Frame, node: ast.expr) -> object:
        self._step()
        try:
            return self._eval_inner(frame, node)
        except _Budget:
            raise
        except RecursionError:
            raise
        except Exception:
            if self._debug:
                raise
            return UNKNOWN

    def _eval_inner(self, frame: _Frame, node: ast.expr) -> object:
        if isinstance(node, ast.Constant):
            return Const(node.value)
        if isinstance(node, ast.Name):
            return self._eval_name(frame, node)
        if isinstance(node, ast.Attribute):
            return self._eval_attribute(frame, node)
        if isinstance(node, ast.Subscript):
            return self._index(frame, node)
        if isinstance(node, ast.Call):
            return self._eval_call(frame, node)
        if isinstance(node, ast.BinOp):
            left = self._eval(frame, node.left)
            right = self._eval(frame, node.right)
            return self._binop(frame, node.op, left, right, node.lineno)
        if isinstance(node, ast.UnaryOp):
            val = self._eval(frame, node.operand)
            if isinstance(node.op, ast.USub):
                if isinstance(val, Const) and isinstance(val.value, (int, float)):
                    return Const(-val.value)
                if isinstance(val, Arr):
                    return val
                return UNKNOWN
            if isinstance(node.op, ast.UAdd):
                return val
            if isinstance(node.op, ast.Not):
                truth = _truthiness(val)
                return Const(not truth) if truth is not None else UNKNOWN
            if isinstance(node.op, ast.Invert) and isinstance(val, Arr):
                return val
            return UNKNOWN
        if isinstance(node, ast.Compare):
            return self._compare(frame, node)
        if isinstance(node, ast.BoolOp):
            vals = [self._eval(frame, v) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                out = self._join(out, v)
            return out
        if isinstance(node, ast.IfExp):
            cond = self._eval(frame, node.test)
            truth = _truthiness(cond)
            if truth is True:
                return self._eval(frame, node.body)
            if truth is False:
                return self._eval(frame, node.orelse)
            return self._join(self._eval(frame, node.body),
                              self._eval(frame, node.orelse))
        if isinstance(node, (ast.Tuple, ast.List)):
            if any(isinstance(e, ast.Starred) for e in node.elts):
                return UNKNOWN
            return TupVal(tuple(self._eval(frame, e) for e in node.elts))
        if isinstance(node, ast.Lambda):
            return FnVal(module=frame.module, node=node, closure=frame.env)
        if isinstance(node, ast.NamedExpr):
            val = self._eval(frame, node.value)
            self._assign(frame, node.target, val)
            return val
        if isinstance(node, ast.Starred):
            return UNKNOWN
        # Dict/Set/comprehensions/f-strings/await/yield: opaque.
        return UNKNOWN

    def _eval_name(self, frame: _Frame, node: ast.Name) -> object:
        name = node.id
        if name in frame.env:
            return frame.env[name]
        module_env = self._module_env.get(id(frame.module))
        if module_env is not None and name in module_env:
            return module_env[name]
        if name in ("True", "False", "None"):  # pre-3.8 trees only
            return Const({"True": True, "False": False, "None": None}[name])
        if name in ("bool", "int", "float", "complex"):
            return DtypeVal({"bool": "bool", "int": "int32",
                             "float": "float32", "complex": "complex64"}[name])
        aliases = self.graph.aliases(frame.module)
        target = aliases.get(name)
        if target is not None:
            return self._resolve_dotted(frame, target)
        fi = self.graph.resolve_function(frame.module, name)
        if fi is not None:
            return FnVal(module=fi.module, node=fi.node)
        s = self.graph.resolve_string(frame.module, node)
        if s is not None:
            return Const(s)
        if name in __builtins__ if isinstance(__builtins__, dict) else hasattr(__builtins__, name):
            return FnVal(builtin=name)
        return UNKNOWN

    def _resolve_dotted(self, frame: _Frame, name: str) -> object:
        """The value a canonical dotted name denotes (dtype, const,
        project function, cross-module global, or a ModRef prefix)."""
        if name in _ATTR_CONSTS:
            return Const(_ATTR_CONSTS[name])
        head, _, tail = name.rpartition(".")
        if tail in _DTYPE_NAMES and head in ("jax.numpy", "numpy", "jax.dtypes"):
            return DtypeVal(tail)
        fi = self.graph.resolve_function(frame.module, name)
        if fi is not None:
            return FnVal(module=fi.module, node=fi.node)
        if head in self._by_name:
            owner = self._by_name[head]
            env = self._env_of(owner)
            if tail in env:
                return env[tail]
        return ModRef(name)

    _ARR_REDUCE_METHODS = _REDUCTIONS | {"ptp"}

    def _eval_attribute(self, frame: _Frame, node: ast.Attribute) -> object:
        # Prefer whole-chain dotted resolution when the base name is not a
        # local binding (``jnp.float32``, ``np.inf``, ``mod.fn``).
        base = node
        while isinstance(base, ast.Attribute):
            base = base.value
        aliases = self.graph.aliases(frame.module)
        if isinstance(base, ast.Name) and base.id not in frame.env and \
                base.id not in self._module_env.get(id(frame.module), {}):
            name = dotted(node, aliases)
            if name is not None:
                resolved = self._resolve_dotted(frame, name)
                if not isinstance(resolved, ModRef):
                    return resolved
                return resolved
        val = self._eval(frame, node.value)
        attr = node.attr
        if isinstance(val, Arr):
            if attr == "shape":
                if val.dims is None:
                    return UNKNOWN
                return TupVal(tuple(_dim_to_val(d) for d in val.dims))
            if attr == "dtype":
                return DtypeVal(val.dtype) if val.dtype else UNKNOWN
            if attr == "ndim":
                return Const(len(val.dims)) if val.dims is not None else UNKNOWN
            if attr == "size":
                if val.dims is not None and all(isinstance(d, int) for d in val.dims):
                    n = 1
                    for d in val.dims:
                        n *= d
                    return Const(n)
                return UNKNOWN
            if attr == "T":
                if val.dims is None:
                    return Arr(None, val.dtype)
                return Arr(tuple(reversed(val.dims)), val.dtype,
                           chain=extend_chain(val.chain, node.lineno,
                                              f".T -> {fmt_dims(tuple(reversed(val.dims)))}"))
            if attr == "at":
                return MethodVal(val, "at")
            return MethodVal(val, attr)
        if isinstance(val, AtIdxVal):
            return MethodVal(val, attr)
        if isinstance(val, MeshVal):
            if attr == "shape":
                return MeshShapeVal(val)
            if attr == "axis_names":
                return TupVal(tuple(Const(a) for a in val.axes))
            if attr == "size":
                n = 1
                for s in val.sizes:
                    if not isinstance(s, int):
                        return UNKNOWN
                    n *= s
                return Const(n)
            return UNKNOWN
        if isinstance(val, MethodVal) and val.attr == "at":
            return UNKNOWN
        if isinstance(val, ModRef):
            return self._resolve_dotted(frame, f"{val.name}.{attr}")
        if isinstance(val, (TupVal, Const, ShardingVal, SpecVal)):
            return MethodVal(val, attr)
        return UNKNOWN

    def _compare(self, frame: _Frame, node: ast.Compare) -> object:
        left = self._eval(frame, node.left)
        result: object = None
        for op, rhs_node in zip(node.ops, node.comparators):
            right = self._eval(frame, rhs_node)
            one = self._compare_one(frame, op, left, right, node.lineno)
            result = one if result is None else self._join(result, one)
            left = right
        return result if result is not None else UNKNOWN

    def _compare_one(self, frame: _Frame, op, left, right, line) -> object:
        if isinstance(left, Arr) or isinstance(right, Arr):
            return self._broadcast_op(frame, left, right, line,
                                      "comparison", bool_result=True)
        if isinstance(left, Const) and isinstance(right, Const):
            try:
                if isinstance(op, ast.Eq):
                    return Const(left.value == right.value)
                if isinstance(op, ast.NotEq):
                    return Const(left.value != right.value)
                if isinstance(op, ast.Lt):
                    return Const(left.value < right.value)
                if isinstance(op, ast.LtE):
                    return Const(left.value <= right.value)
                if isinstance(op, ast.Gt):
                    return Const(left.value > right.value)
                if isinstance(op, ast.GtE):
                    return Const(left.value >= right.value)
                if isinstance(op, ast.In):
                    return Const(left.value in right.value)
                if isinstance(op, ast.NotIn):
                    return Const(left.value not in right.value)
                if isinstance(op, ast.Is):
                    return Const(left.value is right.value)
                if isinstance(op, ast.IsNot):
                    return Const(left.value is not right.value)
            except Exception:
                return UNKNOWN
        return UNKNOWN

    # -- operators ---------------------------------------------------------

    def _binop(self, frame: _Frame, op, left, right, line: int) -> object:
        if isinstance(op, ast.MatMult):
            return self._matmul(frame, left, right, line, {})
        if isinstance(left, Arr) or isinstance(right, Arr):
            opname = type(op).__name__.lower()
            return self._broadcast_op(frame, left, right, line, opname)
        if isinstance(left, Const) and isinstance(right, Const):
            lv, rv = left.value, right.value
            num = (int, float)
            if isinstance(lv, num) and isinstance(rv, num) and \
                    not isinstance(lv, bool) and not isinstance(rv, bool):
                try:
                    if isinstance(op, ast.Add):
                        return Const(lv + rv)
                    if isinstance(op, ast.Sub):
                        return Const(lv - rv)
                    if isinstance(op, ast.Mult):
                        return Const(lv * rv)
                    if isinstance(op, ast.Div):
                        return Const(lv / rv)
                    if isinstance(op, ast.FloorDiv):
                        return Const(lv // rv)
                    if isinstance(op, ast.Mod):
                        return Const(lv % rv)
                    if isinstance(op, ast.Pow):
                        return Const(lv ** rv)
                except Exception:
                    return UNKNOWN
            if isinstance(lv, str) and isinstance(rv, str) and \
                    isinstance(op, ast.Add):
                return Const(lv + rv)
            if isinstance(lv, tuple) and isinstance(rv, tuple) and \
                    isinstance(op, ast.Add):
                return Const(lv + rv)
        if isinstance(left, TupVal) and isinstance(right, TupVal) and \
                isinstance(op, ast.Add):
            return TupVal(left.items + right.items)
        return UNKNOWN

    def _operand_info(self, val: object):
        """(dims, dtype, weak, chain) of one broadcast operand."""
        if isinstance(val, Arr):
            return val.dims, val.dtype, False, val.chain
        if isinstance(val, Const) and isinstance(val.value, (int, float, bool)):
            return (), None, True, ()  # python scalar: weak type
        if isinstance(val, Sym):
            return (), None, True, ()
        return None, None, True, ()

    def _broadcast_op(self, frame: _Frame, left, right, line: int,
                      opname: str, bool_result: bool = False) -> object:
        ldims, ldt, lweak, lchain = self._operand_info(left)
        rdims, rdt, rweak, rchain = self._operand_info(right)
        if not isinstance(left, (Arr, Const, Sym)) or \
                not isinstance(right, (Arr, Const, Sym)):
            return UNKNOWN
        dims = self._broadcast_dims(frame, ldims, rdims, line, opname,
                                    lchain or rchain, left, right)
        if bool_result:
            dtype: Optional[str] = "bool"
        elif lweak and not rweak:
            dtype = rdt
        elif rweak and not lweak:
            dtype = ldt
        else:
            dtype = promote_dtype(ldt, rdt)
        chain = lchain if isinstance(left, Arr) else rchain
        out = Arr(dims, dtype, chain=chain)
        if not bool_result:
            self._check_promotion(frame, line, out, (ldt, rdt), opname)
        if isinstance(out.dims, tuple):
            out.chain = extend_chain(
                chain, line, f"{opname} -> {fmt_arr(out)}"
            )
        return out

    def _broadcast_dims(self, frame: _Frame, ldims, rdims, line: int,
                        opname: str, chain: Chain, left=None, right=None):
        if ldims is None or rdims is None:
            return None
        out: List[object] = []
        la, ra = list(ldims), list(rdims)
        while len(la) < len(ra):
            la.insert(0, 1)
        while len(ra) < len(la):
            ra.insert(0, 1)
        for dl, dr in zip(la, ra):
            if isinstance(dl, int) and isinstance(dr, int):
                if dl == dr or dr == 1:
                    out.append(dl if dr == 1 or dl == dr else dr)
                elif dl == 1:
                    out.append(dr)
                else:
                    lrend = fmt_arr(left) if isinstance(left, Arr) else repr(dl)
                    rrend = fmt_arr(right) if isinstance(right, Arr) else repr(dr)
                    self._emit(
                        "shape-mismatch", frame, line,
                        f"operands of {opname} do not broadcast: "
                        f"{lrend} vs {rrend} (dim {dl} vs {dr}, neither is 1)",
                        chain,
                    )
                    out.append(DYN)
            elif dl is dr:
                out.append(dl)
            elif isinstance(dl, int) and dl == 1:
                out.append(dr)
            elif isinstance(dr, int) and dr == 1:
                out.append(dl)
            else:
                out.append(DYN)
        return tuple(out)

    def _check_promotion(self, frame: _Frame, line: int, result: Arr,
                         operand_dtypes, opname: str) -> None:
        """dtype-promotion: rank>=1 float64 appearing from mixed operands
        inside traced code. Rank-0 f64 scalars are ignored (JAX's default
        x64-disabled canonicalization makes them harmless weak scalars)."""
        if not frame.traced or result.dtype != "float64":
            return
        if result.dims is None or len(result.dims) == 0:
            return
        known = [d for d in operand_dtypes if d]
        if not known or all(d == "float64" for d in known):
            return
        fromtxt = " x ".join(sorted(set(known)))
        self._emit(
            "dtype-promotion", frame, line,
            f"{opname} promotes {fromtxt} to a float64 array "
            f"({fmt_arr(result)}) inside traced code; TPUs have no f64 "
            "units and default x64-disabled JAX silently truncates — cast "
            "the operand to float32 (or jnp.asarray it) instead",
            result.chain,
        )

    # -- calls -------------------------------------------------------------

    def _eval_call(self, frame: _Frame, node: ast.Call) -> object:
        aliases = self.graph.aliases(frame.module)
        args_unknown = any(isinstance(a, ast.Starred) for a in node.args)
        args = [] if args_unknown else [self._eval(frame, a) for a in node.args]
        if args_unknown:
            for a in node.args:
                inner = a.value if isinstance(a, ast.Starred) else a
                self._eval(frame, inner)
        kwargs, kw_splat = self._eval_kwargs(frame, node)

        base = node.func
        while isinstance(base, ast.Attribute):
            base = base.value
        base_local = (
            isinstance(base, ast.Name)
            and (base.id in frame.env
                 or base.id in self._module_env.get(id(frame.module), {}))
        )
        name = None if base_local else callee_name(node, aliases)
        if name is not None:
            out = self._call_builtin(frame, name, args, kwargs, node,
                                     args_unknown, kw_splat)
            if out is not NotImplemented:
                return out
            fi = self.graph.resolve_function(frame.module, name)
            if fi is not None:
                if args_unknown:
                    return UNKNOWN
                return self._call_project(
                    fi.module, fi.node, None, args, kwargs, frame,
                    node.lineno, kw_unknown=kw_splat)
        func = self._eval(frame, node.func)
        if args_unknown:
            return UNKNOWN
        return self._call_value(frame, func, args, kwargs, node.lineno,
                                kw_unknown=kw_splat)

    def _call_value(self, frame: _Frame, func: object, args: List[object],
                    kwargs: Dict[str, object], line: int,
                    kw_unknown: bool = False) -> object:
        if isinstance(func, FnVal):
            merged_args = list(func.bound_args) + list(args)
            merged_kwargs = dict(func.bound_kwargs or {})
            merged_kwargs.update(kwargs)
            kw_unk = kw_unknown or func.kw_unknown
            if func.builtin is not None:
                out = self._call_builtin(frame, func.builtin, merged_args,
                                         merged_kwargs, None, False, kw_unk,
                                         line=line)
                return UNKNOWN if out is NotImplemented else out
            if func.node is not None:
                return self._call_project(
                    func.module, func.node, func.closure, merged_args,
                    merged_kwargs, frame, line, kw_unknown=kw_unk)
            return UNKNOWN
        if isinstance(func, XformVal):
            return self._apply_xform(frame, func, args, kwargs, line)
        if isinstance(func, MethodVal):
            return self._call_method(frame, func.obj, func.attr, args,
                                     kwargs, line)
        if isinstance(func, DtypeVal):
            if len(args) == 1:
                return self._cast(frame, args[0], func.name, line)
            return UNKNOWN
        if isinstance(func, LayerVal):
            return self._call_layer(frame, func, args, kwargs, line)
        return UNKNOWN

    def _cast(self, frame: _Frame, val: object, dtype: str, line: int) -> object:
        """``jnp.float32(x)`` / ``x.astype(dt)`` — explicit, never flagged."""
        if isinstance(val, Const) and isinstance(val.value, (int, float, bool)):
            try:
                if dtype == "bool":
                    return Const(bool(val.value))
                if dtype.startswith(("int", "uint")):
                    return Const(int(val.value))
                if dtype.startswith(("float", "bfloat")):
                    return Arr((), dtype)
            except Exception:
                return UNKNOWN
        if isinstance(val, Arr):
            return Arr(val.dims, dtype, val.spec,
                       extend_chain(val.chain, line, f"astype {dtype}"))
        return UNKNOWN

    def _call_project(self, module: Optional[ModuleInfo], node, closure,
                      args: List[object], kwargs: Dict[str, object],
                      frame: _Frame, line: int, kw_unknown: bool,
                      contract_defaults: bool = False) -> object:
        """Interpret a project function call; returns the joined return."""
        if module is None or node is None:
            return UNKNOWN
        if frame.depth >= _MAX_DEPTH or id(node) in frame.stack:
            return UNKNOWN
        env: Dict[str, object] = dict(closure) if closure else {}
        a = node.args
        pos_params = [p.arg for p in a.posonlyargs + a.args]
        defaults = list(a.defaults)
        default_for: Dict[str, ast.expr] = {}
        for pname, dnode in zip(pos_params[len(pos_params) - len(defaults):],
                                defaults):
            default_for[pname] = dnode
        for pname, dnode in zip([p.arg for p in a.kwonlyargs], a.kw_defaults):
            if dnode is not None:
                default_for[pname] = dnode
        all_params = pos_params + [p.arg for p in a.kwonlyargs]

        def bind_default(pname: str) -> object:
            dnode = default_for.get(pname)
            if dnode is None:
                return UNKNOWN
            dframe = _Frame(module=module,
                            env=dict(self._module_env.get(id(module), {})),
                            traced=False, axis_env={}, depth=frame.depth,
                            stack=frame.stack)
            return self._eval(dframe, dnode)

        for i, pname in enumerate(pos_params):
            if i < len(args):
                env[pname] = args[i]
            elif pname in kwargs:
                env[pname] = kwargs[pname]
            elif kw_unknown:
                env[pname] = UNKNOWN
            else:
                env[pname] = bind_default(pname)
        for pname in [p.arg for p in a.kwonlyargs]:
            if pname in kwargs:
                env[pname] = kwargs[pname]
            elif kw_unknown:
                env[pname] = UNKNOWN
            else:
                env[pname] = bind_default(pname)
        if a.vararg is not None:
            extra = args[len(pos_params):]
            env[a.vararg.arg] = TupVal(tuple(extra)) if extra else TupVal(())
        if a.kwarg is not None:
            env[a.kwarg.arg] = UNKNOWN
        if contract_defaults:
            # entry interpretation: contract-named params only; the rest
            # keep UNKNOWN (kwargs here IS the contract table)
            for pname in all_params:
                if pname not in kwargs:
                    env.setdefault(pname, UNKNOWN)

        inner = _Frame(
            module=module, env=env, traced=frame.traced,
            axis_env=dict(frame.axis_env), depth=frame.depth + 1,
            stack=frame.stack | {id(node)},
        )
        body = node.body if isinstance(node.body, list) else None
        if body is None:  # lambda
            return self._eval(inner, node.body)
        self._exec_block(inner, body)
        if not inner.returns:
            return Const(None)
        out = inner.returns[0]
        for other in inner.returns[1:]:
            out = self._join(out, other)
        return out

    # -- subscripting ------------------------------------------------------

    def _index(self, frame: _Frame, node: ast.Subscript) -> object:
        base = self._eval(frame, node.value)
        sl = node.slice
        if isinstance(base, TupVal):
            idx = self._eval(frame, sl) if not isinstance(sl, ast.Slice) else None
            if isinstance(sl, ast.Slice):
                lo = self._eval(frame, sl.lower) if sl.lower else Const(None)
                hi = self._eval(frame, sl.upper) if sl.upper else Const(None)
                st = self._eval(frame, sl.step) if sl.step else Const(None)
                if all(isinstance(v, Const) for v in (lo, hi, st)):
                    try:
                        return TupVal(tuple(
                            base.items[slice(lo.value, hi.value, st.value)]))
                    except Exception:
                        return UNKNOWN
                return UNKNOWN
            if isinstance(idx, Const) and isinstance(idx.value, int):
                try:
                    return base.items[idx.value]
                except IndexError:
                    return UNKNOWN
            return UNKNOWN
        if isinstance(base, MeshShapeVal):
            idx = self._eval(frame, sl)
            if isinstance(idx, Const) and isinstance(idx.value, str):
                mesh = base.mesh
                if idx.value in mesh.axes:
                    size = mesh.sizes[mesh.axes.index(idx.value)]
                    return Const(size) if isinstance(size, int) else UNKNOWN
            return UNKNOWN
        if isinstance(base, AtIdxVal):
            return base
        if isinstance(base, MethodVal) and base.attr == "at":
            # x.at[idx] — remember the array, updates preserve its shape
            if isinstance(base.obj, Arr):
                self._eval(frame, sl) if not isinstance(sl, ast.Slice) else None
                return AtIdxVal(base.obj)
            return UNKNOWN
        if isinstance(base, Const) and isinstance(base.value, (tuple, str)):
            idx = self._eval(frame, sl) if not isinstance(sl, ast.Slice) else None
            if isinstance(idx, Const) and isinstance(idx.value, int):
                try:
                    return Const(base.value[idx.value])
                except Exception:
                    return UNKNOWN
            return UNKNOWN
        if not isinstance(base, Arr):
            if not isinstance(sl, ast.Slice):
                self._eval(frame, sl)
            return UNKNOWN
        if base.dims is None:
            return Arr(None, base.dtype)
        parts = list(sl.elts) if isinstance(sl, ast.Tuple) else [sl]
        has_ellipsis = any(
            isinstance(p, ast.Constant) and p.value is Ellipsis for p in parts)
        front: List[ast.expr] = []
        back: List[ast.expr] = []
        seen_ell = False
        for p in parts:
            if isinstance(p, ast.Constant) and p.value is Ellipsis:
                seen_ell = True
                continue
            (back if seen_ell else front).append(p)
        dims = list(base.dims)
        out_front: List[object] = []
        out_back: List[object] = []

        def consume(p: ast.expr, dim_pool: List[object], out: List[object],
                    from_back: bool) -> bool:
            """Apply one index part; returns False on fancy/unknown rank."""
            if isinstance(p, ast.Slice):
                if not dim_pool:
                    return True
                d = dim_pool.pop(0 if not from_back else -1)
                lo = self._eval(frame, p.lower) if p.lower else Const(None)
                hi = self._eval(frame, p.upper) if p.upper else Const(None)
                st = self._eval(frame, p.step) if p.step else Const(None)
                if isinstance(d, int) and all(
                        isinstance(v, Const) and
                        (v.value is None or isinstance(v.value, int))
                        for v in (lo, hi, st)):
                    try:
                        newd = len(range(*slice(lo.value, hi.value,
                                                st.value).indices(d)))
                    except Exception:
                        newd = DYN
                else:
                    full = (lo.value is None if isinstance(lo, Const) else False) and \
                           (hi.value is None if isinstance(hi, Const) else False) and \
                           (st.value is None if isinstance(st, Const) else False)
                    newd = d if full else DYN
                out.append(newd)
                return True
            val = self._eval(frame, p)
            if isinstance(val, Const):
                if val.value is None:
                    out.append(1)
                    return True
                if isinstance(val.value, int):
                    if dim_pool:
                        dim_pool.pop(0 if not from_back else -1)
                    return True
                return False
            if isinstance(val, Arr):
                if val.dims == ():
                    if dim_pool:
                        dim_pool.pop(0 if not from_back else -1)
                    return True
                return False  # fancy indexing: give up on rank
            if dim_pool:  # unknown scalar-ish index: drop one dim
                dim_pool.pop(0 if not from_back else -1)
            return True

        for p in front:
            if not consume(p, dims, out_front, from_back=False):
                return Arr(None, base.dtype)
        if has_ellipsis:
            for p in reversed(back):
                tmp: List[object] = []
                if not consume(p, dims, tmp, from_back=True):
                    return Arr(None, base.dtype)
                out_back = tmp + out_back
            new_dims = tuple(out_front) + tuple(dims) + tuple(out_back)
        else:
            new_dims = tuple(out_front) + tuple(dims)
        return Arr(new_dims, base.dtype,
                   chain=extend_chain(base.chain, node.lineno,
                                      f"index -> {fmt_dims(new_dims)}"))

    # -- matmul / einsum ---------------------------------------------------

    def _matmul(self, frame: _Frame, left, right, line: int,
                kwargs: Dict[str, object]) -> object:
        if not isinstance(left, Arr) or not isinstance(right, Arr):
            return UNKNOWN
        dtype = self._einsum_dtype(kwargs, left.dtype, right.dtype)
        if left.dims is None or right.dims is None:
            return Arr(None, dtype)
        ld, rd = left.dims, right.dims
        if len(ld) == 0 or len(rd) == 0:
            return UNKNOWN
        lk = ld[-1]
        rk = rd[-2] if len(rd) >= 2 else rd[-1]
        if isinstance(lk, int) and isinstance(rk, int) and lk != rk:
            self._emit(
                "shape-mismatch", frame, line,
                f"matmul contracting dims disagree: {fmt_arr(left)} @ "
                f"{fmt_arr(right)} ({lk} vs {rk})",
                left.chain or right.chain,
            )
            return Arr(None, dtype)
        if len(ld) == 1 and len(rd) == 1:
            dims: Tuple = ()
        elif len(rd) == 1:
            dims = ld[:-1]
        elif len(ld) == 1:
            dims = rd[:-2] + (rd[-1],)
        else:
            batch = self._broadcast_dims(frame, ld[:-2], rd[:-2], line,
                                         "matmul batch", left.chain)
            if batch is None:
                return Arr(None, dtype)
            dims = tuple(batch) + (ld[-2], rd[-1])
        out = Arr(dims, dtype,
                  chain=extend_chain(left.chain or right.chain, line,
                                     f"matmul -> {fmt_dims(dims)}"))
        return out

    @staticmethod
    def _einsum_dtype(kwargs: Dict[str, object], *dtypes) -> Optional[str]:
        pet = kwargs.get("preferred_element_type")
        if isinstance(pet, DtypeVal):
            return pet.name
        out = None
        for d in dtypes:
            out = promote_dtype(out, d)
        return out

    def _einsum(self, frame: _Frame, args: List[object],
                kwargs: Dict[str, object], line: int) -> object:
        if not args or not isinstance(args[0], Const) or \
                not isinstance(args[0].value, str):
            return UNKNOWN
        spec = args[0].value.replace(" ", "")
        operands = args[1:]
        if "->" not in spec:
            return UNKNOWN
        lhs, rhs = spec.split("->", 1)
        in_specs = lhs.split(",")
        if len(in_specs) != len(operands):
            return UNKNOWN
        if "." in spec:
            return UNKNOWN  # '...' batching: out of scope, stay silent
        binding: Dict[str, object] = {}
        chain: Chain = ()
        dtypes: List[Optional[str]] = []
        for ispec, op in zip(in_specs, operands):
            if not isinstance(op, Arr):
                return UNKNOWN
            dtypes.append(op.dtype)
            chain = chain or op.chain
            if op.dims is None:
                for letter in ispec:
                    binding.setdefault(letter, DYN)
                continue
            if len(ispec) != len(op.dims):
                self._emit(
                    "shape-mismatch", frame, line,
                    f"einsum operand '{ispec}' expects rank {len(ispec)} "
                    f"but got {fmt_arr(op)}",
                    op.chain,
                )
                return UNKNOWN
            for letter, dim in zip(ispec, op.dims):
                prev = binding.get(letter)
                if prev is None:
                    binding[letter] = dim
                elif isinstance(prev, int) and isinstance(dim, int) and \
                        prev != dim:
                    self._emit(
                        "shape-mismatch", frame, line,
                        f"einsum index '{letter}' bound to both {prev} and "
                        f"{dim} across operands of '{spec}'",
                        op.chain or chain,
                    )
                    binding[letter] = DYN
                elif prev is not dim and not (
                        isinstance(prev, int) and isinstance(dim, int)):
                    if isinstance(dim, int):
                        binding[letter] = dim
        dims = tuple(binding.get(letter, DYN) for letter in rhs)
        dtype = self._einsum_dtype(kwargs, *dtypes)
        return Arr(dims, dtype,
                   chain=extend_chain(chain, line,
                                      f"einsum '{spec}' -> {fmt_dims(dims)}"))

    # -- bound-method calls ------------------------------------------------

    _METHOD_TO_BUILTIN = {
        "reshape": "jax.numpy.reshape", "transpose": "jax.numpy.transpose",
        "swapaxes": "jax.numpy.swapaxes", "squeeze": "jax.numpy.squeeze",
        "sum": "jax.numpy.sum", "mean": "jax.numpy.mean",
        "max": "jax.numpy.max", "min": "jax.numpy.min",
        "prod": "jax.numpy.prod", "std": "jax.numpy.std",
        "var": "jax.numpy.var", "all": "jax.numpy.all",
        "any": "jax.numpy.any", "argmax": "jax.numpy.argmax",
        "argmin": "jax.numpy.argmin", "cumsum": "jax.numpy.cumsum",
        "round": "jax.numpy.round", "clip": "jax.numpy.clip",
        "ravel": "jax.numpy.ravel", "flatten": "jax.numpy.ravel",
        "conj": "jax.numpy.conj", "copy": "jax.numpy.copy",
        "repeat": "jax.numpy.repeat", "take": "jax.numpy.take",
    }

    def _call_method(self, frame: _Frame, obj: object, attr: str,
                     args: List[object], kwargs: Dict[str, object],
                     line: int) -> object:
        if isinstance(obj, AtIdxVal):
            if attr in ("set", "add", "subtract", "multiply", "divide",
                        "min", "max", "power", "apply"):
                base = obj.arr
                if args and isinstance(args[0], Arr) and \
                        isinstance(base, Arr):
                    pass  # update broadcast against a *slice*; stay silent
                return base
            if attr == "get":
                return UNKNOWN
            return UNKNOWN
        if isinstance(obj, Arr):
            if attr == "astype" and args:
                dt = args[0]
                if isinstance(dt, DtypeVal):
                    return self._cast(frame, obj, dt.name, line)
                return Arr(obj.dims, None, obj.spec, obj.chain)
            if attr == "item":
                return UNKNOWN
            if attr in ("tolist", "block_until_ready"):
                return obj if attr == "block_until_ready" else UNKNOWN
            builtin = self._METHOD_TO_BUILTIN.get(attr)
            if builtin is not None:
                out = self._call_builtin(frame, builtin, [obj] + args,
                                         kwargs, None, False, False,
                                         line=line)
                return UNKNOWN if out is NotImplemented else out
            return UNKNOWN
        if isinstance(obj, Const):
            v = obj.value
            if isinstance(v, str):
                if attr in ("lower", "upper", "strip", "replace", "format"):
                    try:
                        return Const(getattr(v, attr)(*[
                            a.value for a in args
                            if isinstance(a, Const)]))
                    except Exception:
                        return UNKNOWN
                if attr in ("startswith", "endswith") and args and \
                        isinstance(args[0], Const):
                    try:
                        return Const(getattr(v, attr)(args[0].value))
                    except Exception:
                        return UNKNOWN
            return UNKNOWN
        if isinstance(obj, TupVal) and attr == "index":
            if args and isinstance(args[0], Const):
                for i, item in enumerate(obj.items):
                    if isinstance(item, Const) and item.value == args[0].value:
                        return Const(i)
            return UNKNOWN
        return UNKNOWN

    def _call_layer(self, frame: _Frame, layer: LayerVal,
                    args: List[object], kwargs: Dict[str, object],
                    line: int) -> object:
        x = args[0] if args else kwargs.get("inputs")
        if not isinstance(x, Arr):
            return UNKNOWN
        kind, meta = layer.kind, layer.meta
        if kind == "dense":
            feat = meta.get("features")
            f = _known_int(feat)
            if x.dims is None:
                return Arr(None, x.dtype)
            dims = x.dims[:-1] + ((f,) if f is not None else (DYN,))
            return Arr(dims, x.dtype,
                       chain=extend_chain(x.chain, line,
                                          f"Dense -> {fmt_dims(dims)}"))
        if kind == "conv":
            return self._conv_shape(frame, x, meta, line)
        if kind in ("dropout", "norm"):
            return x
        return UNKNOWN

    def _conv_shape(self, frame: _Frame, x: Arr, meta: Dict[str, object],
                    line: int) -> object:
        """flax.linen.Conv on NHWC input (the convnet case study)."""
        if x.dims is None or len(x.dims) < 3:
            return Arr(None, x.dtype)
        feat = _known_int(meta.get("features"))
        ks = meta.get("kernel_size")
        strides = meta.get("strides")
        padding = meta.get("padding")
        pad = "SAME"
        if isinstance(padding, Const) and isinstance(padding.value, str):
            pad = padding.value.upper()
        kdims: List[Optional[int]] = []
        if isinstance(ks, TupVal):
            for item in ks.items:
                kdims.append(_known_int(item))
        sdims: List[Optional[int]] = [1] * len(kdims)
        if isinstance(strides, TupVal):
            sdims = [_known_int(i) or 1 for i in strides.items]
        elif _known_int(strides) is not None:
            sdims = [_known_int(strides)] * len(kdims)
        spatial = list(x.dims[1:-1])
        n_sp = len(kdims) if kdims else len(spatial)
        out_sp: List[object] = []
        for i, d in enumerate(spatial):
            if i >= n_sp or not isinstance(d, int):
                out_sp.append(d if i >= n_sp else DYN)
                continue
            k = kdims[i] if i < len(kdims) else None
            s = sdims[i] if i < len(sdims) else 1
            if k is None or s is None:
                out_sp.append(DYN)
            elif pad == "SAME":
                out_sp.append(-(-d // s))
            else:  # VALID
                out_sp.append((d - k) // s + 1 if d >= k else DYN)
        dims = (x.dims[0],) + tuple(out_sp) + \
               ((feat,) if feat is not None else (DYN,))
        return Arr(dims, x.dtype,
                   chain=extend_chain(x.chain, line,
                                      f"Conv -> {fmt_dims(dims)}"))

    # -- sharding checks ---------------------------------------------------

    def _spec_entries(self, spec: object) -> Optional[Tuple]:
        if isinstance(spec, SpecVal):
            return spec.entries
        if isinstance(spec, ShardingVal) and isinstance(spec.spec, SpecVal):
            return spec.spec.entries
        return None

    def _sharding_mesh(self, spec: object) -> Optional[MeshVal]:
        if isinstance(spec, ShardingVal) and isinstance(spec.mesh, MeshVal):
            return spec.mesh
        return None

    def _axis_factor(self, mesh: Optional[MeshVal], axis_env: Dict[str, object],
                     entry: object) -> Tuple[Optional[str], object]:
        """(axis label, size) for one PartitionSpec entry; size may be DYN."""
        names: List[str] = []
        if isinstance(entry, str):
            names = [entry]
        elif isinstance(entry, tuple):
            names = [e for e in entry if isinstance(e, str)]
            if len(names) != len(entry):
                return None, DYN
        else:
            return None, DYN
        total: object = 1
        for nm in names:
            size: object = DYN
            if mesh is not None and nm in mesh.axes:
                size = mesh.sizes[mesh.axes.index(nm)]
            elif nm in axis_env:
                size = axis_env[nm]
            if not isinstance(size, int):
                return "+".join(names), DYN
            total = total * size if isinstance(total, int) else DYN
        return "+".join(names), total

    def _check_sharding(self, frame: _Frame, arr: object, sharding: object,
                        line: int, context: str) -> object:
        """Verify a Spec/NamedSharding against an array; attach the spec."""
        if not isinstance(arr, Arr):
            return arr
        entries = self._spec_entries(sharding)
        if entries is None:
            return arr
        mesh = self._sharding_mesh(sharding)
        if arr.dims is not None:
            for i, entry in enumerate(entries):
                if entry is None or i >= len(arr.dims):
                    continue
                label, size = self._axis_factor(mesh, frame.axis_env, entry)
                if label is None or not isinstance(size, int):
                    continue
                dim = arr.dims[i]
                if isinstance(dim, int) and size > 0 and dim % size != 0:
                    self._emit(
                        "indivisible-sharding", frame, line,
                        f"{context}: dim {i} of {fmt_arr(arr)} is sharded "
                        f"over mesh axis '{label}' of size {size}, but "
                        f"{dim} % {size} != 0",
                        arr.chain,
                    )
        return Arr(arr.dims, arr.dtype, entries,
                   extend_chain(arr.chain, line,
                                f"{context} {fmt_spec(entries)}"))

    def _carry_check(self, frame: _Frame, init: object, out: object,
                     line: int, what: str) -> None:
        if isinstance(init, TupVal) and isinstance(out, TupVal):
            if len(init.items) != len(out.items):
                self._emit(
                    "shape-mismatch", frame, line,
                    f"{what} carry changes structure: {len(init.items)} "
                    f"elements in, {len(out.items)} out",
                    (),
                )
                return
            for a, b in zip(init.items, out.items):
                self._carry_check(frame, a, b, line, what)
            return
        if isinstance(init, Arr) and isinstance(out, Arr):
            if init.dims is None or out.dims is None:
                return
            if len(init.dims) != len(out.dims):
                self._emit(
                    "shape-mismatch", frame, line,
                    f"{what} carry changes rank: {fmt_arr(init)} in, "
                    f"{fmt_arr(out)} out",
                    out.chain or init.chain,
                )
                return
            for a, b in zip(init.dims, out.dims):
                if isinstance(a, int) and isinstance(b, int) and a != b:
                    self._emit(
                        "shape-mismatch", frame, line,
                        f"{what} carry changes shape: {fmt_arr(init)} in, "
                        f"{fmt_arr(out)} out",
                        out.chain or init.chain,
                    )
                    return

    # -- transforms --------------------------------------------------------

    def _apply_xform(self, frame: _Frame, xf: XformVal, args: List[object],
                     kwargs: Dict[str, object], line: int) -> object:
        kind, fn, meta = xf.kind, xf.fn, xf.meta
        if not isinstance(fn, (FnVal, XformVal)):
            return UNKNOWN
        if kind == "jit":
            in_sh = meta.get("in_shardings")
            checked = list(args)
            if isinstance(in_sh, TupVal):
                for i, sh in enumerate(in_sh.items):
                    if i < len(checked):
                        checked[i] = self._check_sharding(
                            frame, checked[i], sh, line, "pjit in_shardings")
            elif in_sh is not None and args:
                checked[0] = self._check_sharding(
                    frame, checked[0], in_sh, line, "pjit in_shardings")
            inner = self._traced(frame)
            return self._call_value(inner, fn, checked, kwargs, line)
        if kind in ("grad", "value_and_grad"):
            inner = self._traced(frame)
            ret = self._call_value(inner, fn, args, kwargs, line)
            grad_like = args[0] if args else UNKNOWN
            if kind == "grad":
                return grad_like
            return TupVal((ret, grad_like))
        if kind in ("vmap", "pmap"):
            return self._apply_vmap(frame, kind, fn, meta, args, kwargs, line)
        if kind == "shard_map":
            return self._apply_shard_map(frame, fn, meta, args, kwargs, line)
        if kind == "pallas_call":
            out_shape = meta.get("out_shape")
            if isinstance(out_shape, Arr):
                return out_shape
            if isinstance(out_shape, TupVal):
                return out_shape
            return UNKNOWN
        return UNKNOWN

    def _traced(self, frame: _Frame) -> _Frame:
        if frame.traced:
            return frame
        inner = self._fork(frame, frame.env)
        inner.traced = True
        return inner

    def _apply_vmap(self, frame: _Frame, kind: str, fn: object,
                    meta: Dict[str, object], args: List[object],
                    kwargs: Dict[str, object], line: int) -> object:
        in_axes = meta.get("in_axes", Const(0))
        out_axes = meta.get("out_axes", Const(0))
        per_arg: List[object]
        if isinstance(in_axes, TupVal):
            if args and len(in_axes.items) != len(args):
                self._emit(
                    "vmap-axis-clash", frame, line,
                    f"{kind} in_axes has {len(in_axes.items)} entries but "
                    f"the mapped function is called with {len(args)} "
                    "positional arguments",
                    (),
                )
                return UNKNOWN
            per_arg = list(in_axes.items)
        else:
            per_arg = [in_axes] * len(args)

        mapped_size: object = DYN
        stripped: List[object] = []
        for i, (arg, ax) in enumerate(zip(args, per_arg)):
            axis = ax.value if isinstance(ax, Const) else None
            if axis is None and isinstance(ax, Const):
                stripped.append(arg)  # in_axes=None: broadcast, keep as-is
                continue
            if not isinstance(arg, Arr) or arg.dims is None:
                stripped.append(UNKNOWN if isinstance(arg, Arr) else arg)
                continue
            if not isinstance(axis, int):
                stripped.append(Arr(None, arg.dtype))
                continue
            rank = len(arg.dims)
            if axis >= rank or axis < -rank:
                self._emit(
                    "vmap-axis-clash", frame, line,
                    f"{kind} in_axes[{i}]={axis} is out of range for "
                    f"argument {i} of rank {rank} ({fmt_arr(arg)})",
                    arg.chain,
                )
                stripped.append(Arr(None, arg.dtype))
                continue
            norm = axis % rank
            size = arg.dims[norm]
            if kind == "vmap":
                if isinstance(size, int):
                    if isinstance(mapped_size, int) and mapped_size != size:
                        self._emit(
                            "vmap-axis-clash", frame, line,
                            f"vmap mapped-axis sizes disagree: argument "
                            f"{i} maps dim of size {size} but an earlier "
                            f"argument mapped size {mapped_size}",
                            arg.chain,
                        )
                    elif mapped_size is DYN:
                        mapped_size = size
                elif isinstance(size, Sym) and mapped_size is DYN:
                    mapped_size = size
            dims = arg.dims[:norm] + arg.dims[norm + 1:]
            stripped.append(Arr(dims, arg.dtype, arg.spec,
                                extend_chain(arg.chain, line,
                                             f"{kind} strip axis {axis} -> "
                                             f"{fmt_dims(dims)}")))
        if kind == "pmap":
            mapped_size = DYN
            axis_name = meta.get("axis_name")
            inner_axis_env = dict(frame.axis_env)
            if isinstance(axis_name, Const) and \
                    isinstance(axis_name.value, str):
                inner_axis_env[axis_name.value] = DYN
        else:
            inner_axis_env = dict(frame.axis_env)
            axis_name = meta.get("axis_name")
            if isinstance(axis_name, Const) and \
                    isinstance(axis_name.value, str):
                inner_axis_env[axis_name.value] = mapped_size

        inner = self._fork(frame, frame.env)
        inner.traced = True
        inner.axis_env = inner_axis_env
        ret = self._call_value(inner, fn, stripped, kwargs, line)

        oax = out_axes.value if isinstance(out_axes, Const) else 0
        if oax is None:
            return ret

        def put_back(v: object) -> object:
            if isinstance(v, Arr):
                if v.dims is None:
                    return Arr(None, v.dtype)
                k = oax if isinstance(oax, int) else 0
                if k < 0:
                    k = len(v.dims) + 1 + k
                k = max(0, min(k, len(v.dims)))
                dims = v.dims[:k] + (mapped_size,) + v.dims[k:]
                return Arr(dims, v.dtype, v.spec,
                           extend_chain(v.chain, line,
                                        f"{kind} out -> {fmt_dims(dims)}"))
            if isinstance(v, TupVal):
                return TupVal(tuple(put_back(i) for i in v.items))
            return UNKNOWN if v is not None else v
        return put_back(ret)

    def _apply_shard_map(self, frame: _Frame, fn: object,
                         meta: Dict[str, object], args: List[object],
                         kwargs: Dict[str, object], line: int) -> object:
        mesh = meta.get("mesh")
        in_specs = meta.get("in_specs")
        out_specs = meta.get("out_specs")
        meshv = mesh if isinstance(mesh, MeshVal) else None

        specs_list: List[object]
        if isinstance(in_specs, TupVal):
            specs_list = list(in_specs.items)
        elif in_specs is not None:
            specs_list = [in_specs] * len(args)
        else:
            specs_list = []

        inner_axis_env = dict(frame.axis_env)
        if meshv is not None:
            for ax, size in zip(meshv.axes, meshv.sizes):
                inner_axis_env[ax] = size if isinstance(size, int) else DYN

        def shard_one(arr: object, spec: object) -> object:
            if not isinstance(arr, Arr) or arr.dims is None:
                return arr
            entries = self._spec_entries(spec)
            if entries is None:
                return Arr(None, arr.dtype)
            dims = list(arr.dims)
            for i, entry in enumerate(entries):
                if entry is None or i >= len(dims):
                    continue
                label, size = self._axis_factor(meshv, frame.axis_env, entry)
                d = dims[i]
                if not isinstance(size, int):
                    dims[i] = DYN
                    continue
                if isinstance(d, int):
                    if size > 0 and d % size != 0:
                        self._emit(
                            "indivisible-sharding", frame, line,
                            f"shard_map in_specs: dim {i} of {fmt_arr(arr)} "
                            f"is sharded over mesh axis '{label}' of size "
                            f"{size}, but {d} % {size} != 0",
                            arr.chain,
                        )
                        dims[i] = DYN
                    else:
                        dims[i] = d // size
                else:
                    dims[i] = DYN
            new = tuple(dims)
            return Arr(new, arr.dtype, None,
                       extend_chain(arr.chain, line,
                                    f"shard_map shard -> {fmt_dims(new)}"))

        sharded = [shard_one(a, specs_list[i] if i < len(specs_list) else None)
                   for i, a in enumerate(args)]
        inner = self._fork(frame, frame.env)
        inner.traced = True
        inner.axis_env = inner_axis_env
        ret = self._call_value(inner, fn, sharded, kwargs, line)

        def unshard_one(v: object, spec: object) -> object:
            if not isinstance(v, Arr) or v.dims is None:
                return v
            entries = self._spec_entries(spec)
            if entries is None:
                return Arr(None, v.dtype)
            dims = list(v.dims)
            for i, entry in enumerate(entries):
                if entry is None or i >= len(dims):
                    continue
                label, size = self._axis_factor(meshv, frame.axis_env, entry)
                d = dims[i]
                if isinstance(size, int) and isinstance(d, int):
                    dims[i] = d * size
                else:
                    dims[i] = DYN
            new = tuple(dims)
            return Arr(new, v.dtype, entries,
                       extend_chain(v.chain, line,
                                    f"shard_map gather -> {fmt_dims(new)}"))

        if isinstance(ret, TupVal) and isinstance(out_specs, TupVal) and \
                len(ret.items) == len(out_specs.items):
            return TupVal(tuple(unshard_one(v, s) for v, s in
                                zip(ret.items, out_specs.items)))
        if isinstance(ret, TupVal):
            return TupVal(tuple(unshard_one(v, out_specs)
                                for v in ret.items))
        return unshard_one(ret, out_specs)

    # -- builtin vocabulary ------------------------------------------------

    def _dims_of(self, val: object) -> Optional[Tuple[object, ...]]:
        """A shape-like value as a dims tuple, else None."""
        if isinstance(val, TupVal):
            return tuple(_val_to_dim(i) for i in val.items)
        if isinstance(val, Const):
            if isinstance(val.value, int) and not isinstance(val.value, bool):
                return (val.value,)
            if isinstance(val.value, tuple) and all(
                    isinstance(v, int) for v in val.value):
                return tuple(val.value)
        if isinstance(val, Sym):
            return (val,)
        return None

    @staticmethod
    def _dtype_of(val: object) -> Optional[str]:
        if isinstance(val, DtypeVal):
            return val.name
        if isinstance(val, Const) and isinstance(val.value, str) and \
                val.value in _DTYPE_NAMES:
            return val.value
        return None

    def _axis_size(self, frame: _Frame, axis_name: object) -> object:
        if isinstance(axis_name, Const) and isinstance(axis_name.value, str):
            return frame.axis_env.get(axis_name.value, DYN)
        return DYN

    @staticmethod
    def _axis_arg(args: List[object], kwargs: Dict[str, object],
                  pos: int = 1) -> object:
        if "axis" in kwargs:
            return kwargs["axis"]
        if len(args) > pos:
            return args[pos]
        return None

    def _reduce_dims(self, arr: Arr, axis_val: object,
                     keepdims: object) -> Optional[Tuple[object, ...]]:
        if arr.dims is None:
            return None
        keep = isinstance(keepdims, Const) and keepdims.value is True
        if axis_val is None or (isinstance(axis_val, Const) and
                                axis_val.value is None):
            return tuple(1 for _ in arr.dims) if keep else ()
        axes: List[int] = []
        if isinstance(axis_val, Const) and isinstance(axis_val.value, int):
            axes = [axis_val.value]
        elif isinstance(axis_val, TupVal):
            for item in axis_val.items:
                k = _known_int(item)
                if k is None:
                    return None
                axes.append(k)
        else:
            return None
        rank = len(arr.dims)
        norm = set()
        for a in axes:
            if -rank <= a < rank:
                norm.add(a % rank)
            else:
                return None
        if keep:
            return tuple(1 if i in norm else d
                         for i, d in enumerate(arr.dims))
        return tuple(d for i, d in enumerate(arr.dims) if i not in norm)

    def _call_builtin(self, frame: _Frame, name: str, args: List[object],
                      kwargs: Dict[str, object], node: Optional[ast.Call],
                      args_unknown: bool = False, kw_splat: bool = False,
                      line: Optional[int] = None) -> object:
        ln = node.lineno if node is not None else (line or 0)
        a0 = args[0] if args else None

        # transform constructors
        if name in _XFORM_KINDS:
            if args_unknown:
                return UNKNOWN
            kind = _XFORM_KINDS[name]
            if not args:
                return FnVal(builtin=name, bound_kwargs=dict(kwargs),
                             kw_unknown=kw_splat)
            meta = dict(kwargs)
            if kind == "shard_map":
                for i, key in enumerate(("mesh", "in_specs", "out_specs")):
                    if len(args) > i + 1:
                        meta.setdefault(key, args[i + 1])
            elif kind in ("vmap", "pmap"):
                for i, key in enumerate(("in_axes", "out_axes")):
                    if len(args) > i + 1:
                        meta.setdefault(key, args[i + 1])
            return XformVal(kind, args[0], meta)

        # meshes, specs, shardings
        if name in MESH_CALLEES:
            return self._make_mesh(frame, name, args, kwargs)
        if name in PARTITION_SPEC_CALLEES:
            entries: List[object] = []
            for arg in args:
                if isinstance(arg, Const) and (
                        arg.value is None or isinstance(arg.value, str)):
                    entries.append(arg.value)
                elif isinstance(arg, TupVal) and all(
                        isinstance(i, Const) and isinstance(i.value, str)
                        for i in arg.items):
                    entries.append(tuple(i.value for i in arg.items))
                else:
                    entries.append(DYN)
            return SpecVal(tuple(entries))
        if name in _NAMED_SHARDING_CALLEES:
            mesh = a0 if isinstance(a0, MeshVal) else kwargs.get("mesh")
            spec = args[1] if len(args) > 1 else kwargs.get("spec")
            return ShardingVal(mesh if isinstance(mesh, MeshVal) else None,
                               spec if isinstance(spec, SpecVal) else None)

        # jax top-level
        if name in ("jax.device_put", "jax.experimental.multihost_utils."
                    "host_local_array_to_global_array"):
            sharding = args[1] if len(args) > 1 else kwargs.get("device")
            if sharding is None:
                return a0 if a0 is not None else UNKNOWN
            if isinstance(a0, TupVal):
                return TupVal(tuple(
                    self._check_sharding(frame, v, sharding, ln, "device_put")
                    for v in a0.items))
            return self._check_sharding(frame, a0, sharding, ln, "device_put")
        if name in ("jax.device_get", "jax.block_until_ready"):
            return a0 if a0 is not None else UNKNOWN
        if name in ("jax.devices", "jax.local_devices"):
            return Arr((DYN,))
        if name in ("jax.device_count", "jax.local_device_count",
                    "jax.process_index", "jax.process_count"):
            return UNKNOWN
        if name == "jax.eval_shape":
            if a0 is not None and not args_unknown:
                inner = self._traced(frame)
                return self._call_value(inner, a0, args[1:], kwargs, ln)
            return UNKNOWN
        if name in ("jax.ShapeDtypeStruct", "jax.core.ShapedArray"):
            dims = self._dims_of(a0 if a0 is not None else
                                 kwargs.get("shape"))
            dt = self._dtype_of(args[1] if len(args) > 1 else
                                kwargs.get("dtype"))
            return Arr(dims, dt)
        if name in ("jax.tree.map", "jax.tree_util.tree_map",
                    "jax.tree_map"):
            return UNKNOWN
        if name in ("jax.debug.print", "jax.debug.callback"):
            return Const(None)

        # jax.lax control flow and collectives
        out = self._call_lax(frame, name, args, kwargs, ln, args_unknown)
        if out is not NotImplemented:
            return out

        # jax.random
        if name.startswith("jax.random."):
            return self._call_random(frame, name[len("jax.random."):],
                                     args, kwargs, ln)

        # jax.nn
        if name.startswith("jax.nn."):
            short = name[len("jax.nn."):]
            if short in _NN_UNARY:
                if isinstance(a0, Arr):
                    dt = a0.dtype
                    if dt is not None and not (
                            dt.startswith("float") or dt.startswith("bfloat")):
                        dt = "float32"
                    return Arr(a0.dims, dt, a0.spec, a0.chain)
                return UNKNOWN
            if short == "one_hot":
                n = _known_int(args[1] if len(args) > 1 else
                               kwargs.get("num_classes"))
                if isinstance(a0, Arr) and a0.dims is not None:
                    dims = a0.dims + ((n,) if n is not None else (DYN,))
                    return Arr(dims, "float32",
                               chain=extend_chain(a0.chain, ln,
                                                  f"one_hot -> {fmt_dims(dims)}"))
                return UNKNOWN
            return UNKNOWN

        # flax layers (constructed then applied)
        if name in ("flax.linen.Dense", "nn.Dense"):
            meta = dict(kwargs)
            if args:
                meta.setdefault("features", args[0])
            return LayerVal("dense", meta)
        if name in ("flax.linen.Conv", "nn.Conv"):
            meta = dict(kwargs)
            for i, key in enumerate(("features", "kernel_size")):
                if len(args) > i:
                    meta.setdefault(key, args[i])
            return LayerVal("conv", meta)
        if name in ("flax.linen.Dropout", "nn.Dropout"):
            return LayerVal("dropout", dict(kwargs))
        if name in ("flax.linen.BatchNorm", "flax.linen.LayerNorm",
                    "flax.linen.GroupNorm", "flax.linen.RMSNorm",
                    "nn.BatchNorm", "nn.LayerNorm"):
            return LayerVal("norm", dict(kwargs))
        if name in ("flax.linen.max_pool", "flax.linen.avg_pool",
                    "nn.max_pool", "nn.avg_pool"):
            return self._pool(frame, args, kwargs, ln)

        # functools / math / python builtins
        if name == "functools.partial":
            return self._make_partial(args, kwargs, kw_splat)
        if name == "functools.reduce":
            return UNKNOWN
        if name.startswith("math."):
            return self._call_math(name[len("math."):], args)
        out = self._call_py_builtin(frame, name, args, kwargs, ln)
        if out is not NotImplemented:
            return out

        # the jnp / np vocabulary
        if name.startswith("jax.numpy."):
            return self._call_jnp(frame, name[len("jax.numpy."):], args,
                                  kwargs, ln, numpy=False)
        if name.startswith("numpy."):
            return self._call_jnp(frame, name[len("numpy."):], args,
                                  kwargs, ln, numpy=True)
        if name.startswith(("jax.", "scipy.", "flax.")):
            return UNKNOWN
        return NotImplemented

    def _make_partial(self, args: List[object], kwargs: Dict[str, object],
                      kw_splat: bool) -> object:
        if not args:
            return UNKNOWN
        target = args[0]
        rest = tuple(args[1:])
        if isinstance(target, ModRef):
            target = FnVal(builtin=target.name)
        if isinstance(target, FnVal):
            merged_kw = dict(target.bound_kwargs or {})
            merged_kw.update(kwargs)
            return FnVal(
                module=target.module, node=target.node,
                closure=target.closure, builtin=target.builtin,
                bound_args=target.bound_args + rest,
                bound_kwargs=merged_kw,
                kw_unknown=target.kw_unknown or kw_splat,
            )
        if isinstance(target, XformVal):
            return UNKNOWN
        return UNKNOWN

    def _call_math(self, short: str, args: List[object]) -> object:
        import math as _math
        a0 = args[0] if args else None
        if short == "prod":
            dims = self._dims_of(a0)
            if dims is not None and all(isinstance(d, int) for d in dims):
                n = 1
                for d in dims:
                    n *= d
                return Const(n)
            return UNKNOWN
        if isinstance(a0, Const) and isinstance(a0.value, (int, float)):
            fn = getattr(_math, short, None)
            if fn is not None:
                try:
                    return Const(fn(*[
                        a.value for a in args if isinstance(a, Const)]))
                except Exception:
                    return UNKNOWN
        return UNKNOWN

    def _call_py_builtin(self, frame: _Frame, name: str, args: List[object],
                         kwargs: Dict[str, object], ln: int) -> object:
        a0 = args[0] if args else None
        if name == "len":
            if isinstance(a0, TupVal):
                return Const(len(a0.items))
            if isinstance(a0, Const) and isinstance(a0.value, (str, tuple)):
                return Const(len(a0.value))
            if isinstance(a0, Arr) and a0.dims:
                return _dim_to_val(a0.dims[0]) if not isinstance(
                    a0.dims[0], Sym) else a0.dims[0]
            return UNKNOWN
        if name in ("tuple", "list"):
            if isinstance(a0, TupVal):
                return a0
            if isinstance(a0, Const) and isinstance(a0.value, tuple):
                return TupVal(tuple(Const(v) for v in a0.value))
            if a0 is None:
                return TupVal(())
            return UNKNOWN
        if name in ("int", "float", "bool", "abs", "round"):
            if isinstance(a0, Const) and isinstance(a0.value, (int, float,
                                                               bool, str)):
                try:
                    return Const({"int": int, "float": float, "bool": bool,
                                  "abs": abs, "round": round}[name](a0.value))
                except Exception:
                    return UNKNOWN
            if name == "abs" and isinstance(a0, Arr):
                return a0
            return UNKNOWN
        if name in ("min", "max", "sum"):
            vals = args if len(args) > 1 else (
                list(a0.items) if isinstance(a0, TupVal) else None)
            if vals and all(isinstance(v, Const) and
                            isinstance(v.value, (int, float))
                            for v in vals):
                try:
                    return Const({"min": min, "max": max, "sum": sum}[name](
                        [v.value for v in vals]))
                except Exception:
                    return UNKNOWN
            return UNKNOWN
        if name == "getattr":
            if isinstance(a0, ModRef) and len(args) > 1 and \
                    isinstance(args[1], Const) and \
                    isinstance(args[1].value, str):
                return self._resolve_dotted(frame,
                                            f"{a0.name}.{args[1].value}")
            return UNKNOWN
        if name in ("isinstance", "hasattr", "callable", "issubclass"):
            return UNKNOWN
        if name == "print":
            return Const(None)
        if name in ("range", "enumerate", "zip", "map", "filter", "sorted",
                    "reversed", "dict", "set", "frozenset", "iter", "next",
                    "vars", "repr", "str", "format", "id", "hash", "type",
                    "divmod", "any", "all"):
            return UNKNOWN
        return NotImplemented

    def _make_mesh(self, frame: _Frame, name: str, args: List[object],
                   kwargs: Dict[str, object]) -> object:
        is_make = name.endswith("make_mesh")
        axes_val = args[1] if len(args) > 1 else kwargs.get(
            "axis_names", kwargs.get("axis_name"))
        axes: List[str] = []
        if isinstance(axes_val, TupVal):
            for item in axes_val.items:
                if isinstance(item, Const) and isinstance(item.value, str):
                    axes.append(item.value)
                else:
                    return UNKNOWN
        elif isinstance(axes_val, Const) and isinstance(axes_val.value, str):
            axes = [axes_val.value]
        elif isinstance(axes_val, Const) and isinstance(axes_val.value, tuple) \
                and all(isinstance(v, str) for v in axes_val.value):
            axes = list(axes_val.value)
        else:
            return UNKNOWN
        sizes: List[object] = [DYN] * len(axes)
        first = args[0] if args else kwargs.get(
            "axis_shapes" if is_make else "devices")
        if is_make:
            dims = self._dims_of(first)
            if dims is not None:
                for i in range(min(len(axes), len(dims))):
                    sizes[i] = dims[i] if isinstance(dims[i], int) else DYN
        elif isinstance(first, Arr) and first.dims is not None:
            for i in range(min(len(axes), len(first.dims))):
                d = first.dims[i]
                sizes[i] = d if isinstance(d, int) else DYN
        return MeshVal(tuple(axes), tuple(sizes))

    def _pool(self, frame: _Frame, args: List[object],
              kwargs: Dict[str, object], ln: int) -> object:
        x = args[0] if args else kwargs.get("inputs")
        if not isinstance(x, Arr):
            return UNKNOWN
        meta = {
            "features": None,
            "kernel_size": args[1] if len(args) > 1 else
            kwargs.get("window_shape"),
            "strides": args[2] if len(args) > 2 else kwargs.get("strides"),
            "padding": kwargs.get("padding", Const("VALID")),
        }
        out = self._conv_shape(frame, x, meta, ln)
        if isinstance(out, Arr) and out.dims is not None and x.dims:
            # pools keep the channel dim instead of projecting to features
            dims = out.dims[:-1] + (x.dims[-1],)
            return Arr(dims, x.dtype,
                       chain=extend_chain(x.chain, ln,
                                          f"pool -> {fmt_dims(dims)}"))
        return out

    def _call_random(self, frame: _Frame, short: str, args: List[object],
                     kwargs: Dict[str, object], ln: int) -> object:
        a0 = args[0] if args else None
        if short in ("PRNGKey", "key"):
            return Arr((2,), "uint32")
        if short == "split":
            n = _known_int(args[1] if len(args) > 1 else
                           kwargs.get("num", Const(2)))
            return Arr((n if n is not None else DYN, 2), "uint32")
        if short == "fold_in":
            return a0 if isinstance(a0, Arr) else Arr((2,), "uint32")
        if short in ("normal", "uniform", "truncated_normal", "gumbel",
                     "exponential", "laplace", "cauchy", "beta", "gamma",
                     "dirichlet"):
            shape = args[1] if len(args) > 1 else kwargs.get("shape")
            dims = self._dims_of(shape) if shape is not None else ()
            dt = self._dtype_of(kwargs.get("dtype")) or "float32"
            return Arr(dims, dt)
        if short in ("randint", "poisson", "categorical_onehot"):
            shape = kwargs.get("shape")
            dims = self._dims_of(shape) if shape is not None else None
            return Arr(dims, "int32")
        if short == "bernoulli":
            shape = args[2] if len(args) > 2 else kwargs.get("shape")
            if shape is not None:
                return Arr(self._dims_of(shape), "bool")
            p = args[1] if len(args) > 1 else kwargs.get("p")
            if isinstance(p, Arr):
                return Arr(p.dims, "bool")
            return Arr((), "bool")
        if short == "categorical":
            logits = args[1] if len(args) > 1 else kwargs.get("logits")
            axis = kwargs.get("axis", Const(-1))
            if isinstance(logits, Arr) and logits.dims is not None:
                k = _known_int(axis)
                if k is not None and -len(logits.dims) <= k < len(logits.dims):
                    k %= len(logits.dims)
                    return Arr(logits.dims[:k] + logits.dims[k + 1:],
                               "int32")
            return Arr(None, "int32")
        if short in ("permutation", "shuffle", "choice"):
            x = args[1] if len(args) > 1 else None
            if isinstance(x, Arr):
                return Arr(x.dims, x.dtype)
            k = _known_int(x)
            if k is not None:
                return Arr((k,), "int32")
            return UNKNOWN
        return UNKNOWN

    def _call_lax(self, frame: _Frame, name: str, args: List[object],
                  kwargs: Dict[str, object], ln: int,
                  args_unknown: bool) -> object:
        if not name.startswith("jax.lax."):
            return NotImplemented
        short = name[len("jax.lax."):]
        a0 = args[0] if args else None

        if short in ("psum", "pmean", "pmax", "pmin", "psum_scatter",
                     "ppermute", "pshuffle", "pvary", "pcast",
                     "stop_gradient"):
            if isinstance(a0, TupVal):
                return a0
            return a0 if a0 is not None else UNKNOWN
        if short == "axis_index":
            return Arr((), "int32")
        if short == "axis_size":
            n = self._axis_size(frame, a0)
            return Const(n) if isinstance(n, int) else UNKNOWN
        if short == "all_gather":
            if not isinstance(a0, Arr):
                return UNKNOWN
            n = self._axis_size(frame, args[1] if len(args) > 1 else
                                kwargs.get("axis_name"))
            axis = _known_int(kwargs.get("axis", Const(0))) or 0
            tiled = kwargs.get("tiled")
            if a0.dims is None:
                return Arr(None, a0.dtype)
            rank = len(a0.dims)
            if isinstance(tiled, Const) and tiled.value:
                if 0 <= axis < rank:
                    d = a0.dims[axis]
                    newd = d * n if isinstance(d, int) and \
                        isinstance(n, int) else DYN
                    dims = a0.dims[:axis] + (newd,) + a0.dims[axis + 1:]
                else:
                    dims = None
            else:
                axis = max(0, min(axis, rank))
                dims = a0.dims[:axis] + \
                    (n if isinstance(n, int) else DYN,) + a0.dims[axis:]
            return Arr(dims, a0.dtype,
                       chain=extend_chain(a0.chain, ln,
                                          f"all_gather -> {fmt_dims(dims)}"))
        if short == "all_to_all":
            if not isinstance(a0, Arr):
                return UNKNOWN
            n = self._axis_size(frame, args[1] if len(args) > 1 else
                                kwargs.get("axis_name"))
            split = _known_int(args[2] if len(args) > 2 else
                               kwargs.get("split_axis"))
            concat = _known_int(args[3] if len(args) > 3 else
                                kwargs.get("concat_axis"))
            tiled = kwargs.get("tiled")
            is_tiled = isinstance(tiled, Const) and bool(tiled.value)
            if a0.dims is None or split is None or concat is None:
                return Arr(None, a0.dtype)
            if not is_tiled:
                return Arr(None, a0.dtype)
            dims = list(a0.dims)
            rank = len(dims)
            if not (0 <= split < rank and 0 <= concat < rank):
                return Arr(None, a0.dtype)
            d = dims[split]
            if isinstance(n, int):
                if isinstance(d, int):
                    if n > 0 and d % n != 0:
                        self._emit(
                            "indivisible-sharding", frame, ln,
                            f"all_to_all(tiled=True) splits dim {split} of "
                            f"{fmt_arr(a0)} across an axis of size {n}, "
                            f"but {d} % {n} != 0",
                            a0.chain,
                        )
                        dims[split] = DYN
                    else:
                        dims[split] = d // n
                else:
                    dims[split] = DYN
                c = dims[concat]
                dims[concat] = c * n if isinstance(c, int) else DYN
            else:
                dims[split] = DYN
                dims[concat] = DYN
            new = tuple(dims)
            return Arr(new, a0.dtype,
                       chain=extend_chain(a0.chain, ln,
                                          f"all_to_all -> {fmt_dims(new)}"))
        if short == "with_sharding_constraint":
            sharding = args[1] if len(args) > 1 else kwargs.get("shardings")
            if sharding is None:
                return a0 if a0 is not None else UNKNOWN
            return self._check_sharding(frame, a0, sharding, ln,
                                        "with_sharding_constraint")
        if short in ("select", "select_n"):
            for cand in args[1:]:
                if isinstance(cand, Arr):
                    return cand
            return UNKNOWN
        if short == "dynamic_slice":
            sizes = args[-1] if args else None
            dims = self._dims_of(sizes)
            dt = a0.dtype if isinstance(a0, Arr) else None
            return Arr(dims, dt)
        if short in ("dynamic_update_slice", "dynamic_update_slice_in_dim"):
            return a0 if isinstance(a0, Arr) else UNKNOWN
        if short == "iota":
            dt = self._dtype_of(a0)
            n = _known_int(args[1] if len(args) > 1 else kwargs.get("size"))
            return Arr((n if n is not None else DYN,), dt or "int32")
        if short == "broadcasted_iota":
            dt = self._dtype_of(a0)
            dims = self._dims_of(args[1] if len(args) > 1 else
                                 kwargs.get("shape"))
            return Arr(dims, dt or "int32")
        if short == "top_k":
            k = _known_int(args[1] if len(args) > 1 else kwargs.get("k"))
            if isinstance(a0, Arr) and a0.dims is not None:
                dims = a0.dims[:-1] + (k if k is not None else DYN,)
                return TupVal((Arr(dims, a0.dtype, chain=a0.chain),
                               Arr(dims, "int32")))
            return TupVal((Arr(None), Arr(None, "int32")))
        if short == "convert_element_type":
            dt = self._dtype_of(args[1] if len(args) > 1 else
                                kwargs.get("new_dtype"))
            if isinstance(a0, Arr) and dt:
                return self._cast(frame, a0, dt, ln)
            return a0 if isinstance(a0, Arr) else UNKNOWN
        if short in ("exp", "log", "sqrt", "rsqrt", "tanh", "erf", "abs",
                     "neg", "sign", "floor", "ceil", "round", "logistic"):
            return a0 if isinstance(a0, Arr) else UNKNOWN
        if short in ("add", "sub", "mul", "div", "max", "min", "pow",
                     "rem", "atan2"):
            if len(args) >= 2:
                return self._broadcast_op(frame, args[0], args[1], ln,
                                          f"lax.{short}")
            return UNKNOWN

        if short == "fori_loop":
            if len(args) < 4 or args_unknown:
                return UNKNOWN
            body, init = args[2], args[3]
            out = self._call_value(self._traced(frame), body,
                                   [Arr((), "int32"), init], {}, ln)
            self._carry_check(frame, init, out, ln, "fori_loop")
            return self._join(init, out)
        if short == "while_loop":
            if len(args) < 3 or args_unknown:
                return UNKNOWN
            cond, body, init = args[0], args[1], args[2]
            self._call_value(self._traced(frame), cond, [init], {}, ln)
            out = self._call_value(self._traced(frame), body, [init], {}, ln)
            self._carry_check(frame, init, out, ln, "while_loop")
            return self._join(init, out)
        if short == "scan":
            if len(args) < 2 or args_unknown:
                return UNKNOWN
            f, init = args[0], args[1]
            xs = args[2] if len(args) > 2 else kwargs.get("xs")
            lead: object = DYN
            if isinstance(xs, Arr) and xs.dims:
                elem: object = Arr(xs.dims[1:], xs.dtype)
                lead = xs.dims[0]
            elif isinstance(xs, Arr):
                elem = Arr(None, xs.dtype)
            else:
                n = _known_int(kwargs.get("length") or
                               (args[3] if len(args) > 3 else None))
                lead = n if n is not None else DYN
                elem = UNKNOWN
            out = self._call_value(self._traced(frame), f, [init, elem],
                                   {}, ln)
            if isinstance(out, TupVal) and len(out.items) == 2:
                carry, y = out.items
            else:
                carry, y = out, UNKNOWN
            self._carry_check(frame, init, carry, ln, "scan")
            if isinstance(y, Arr) and y.dims is not None:
                ys: object = Arr((lead,) + y.dims, y.dtype)
            elif isinstance(y, Arr):
                ys = Arr(None, y.dtype)
            else:
                ys = UNKNOWN
            return TupVal((self._join(init, carry), ys))
        if short == "cond":
            if len(args) < 3 or args_unknown:
                return UNKNOWN
            operands = args[3:]
            t = self._call_value(self._traced(frame), args[1], list(operands),
                                 {}, ln)
            f = self._call_value(self._traced(frame), args[2], list(operands),
                                 {}, ln)
            return self._join(t, f)
        if short == "switch":
            if len(args) < 2 or args_unknown:
                return UNKNOWN
            branches = args[1]
            operands = args[2:]
            if isinstance(branches, TupVal) and branches.items:
                out = self._call_value(self._traced(frame), branches.items[0],
                                       list(operands), {}, ln)
                for b in branches.items[1:]:
                    out = self._join(out, self._call_value(
                        self._traced(frame), b, list(operands), {}, ln))
                return out
            return UNKNOWN
        if short == "map":
            return UNKNOWN
        if short in ("full", "full_like", "zeros_like", "ones_like"):
            return self._call_jnp(frame, short, args, kwargs, ln,
                                  numpy=False)
        return UNKNOWN

    def _call_jnp(self, frame: _Frame, short: str, args: List[object],
                  kwargs: Dict[str, object], ln: int, numpy: bool) -> object:
        a0 = args[0] if args else None
        default_float = "float64" if numpy else "float32"
        default_int = "int64" if numpy else "int32"

        # creation
        if short in ("zeros", "ones", "empty", "full"):
            shape = a0 if a0 is not None else kwargs.get("shape")
            dims = self._dims_of(shape)
            dt_pos = 2 if short == "full" else 1
            dt = self._dtype_of(args[dt_pos] if len(args) > dt_pos else
                                kwargs.get("dtype")) or default_float
            out = Arr(dims, dt)
            out.chain = extend_chain((), ln, f"jnp.{short} -> {fmt_arr(out)}")
            return out
        if short in ("zeros_like", "ones_like", "empty_like", "full_like"):
            dt = self._dtype_of(kwargs.get("dtype"))
            if isinstance(a0, Arr):
                return Arr(a0.dims, dt or a0.dtype, chain=a0.chain)
            return Arr(None, dt)
        if short in ("asarray", "array"):
            dt = self._dtype_of(args[1] if len(args) > 1 else
                                kwargs.get("dtype"))
            if isinstance(a0, Arr):
                return Arr(a0.dims, dt or a0.dtype, a0.spec, a0.chain)
            if isinstance(a0, Const) and isinstance(a0.value,
                                                    (int, float, bool)):
                return Arr((), dt)
            if isinstance(a0, TupVal):
                return Arr((len(a0.items),), dt)
            return Arr(None, dt)
        if short == "arange":
            dt = self._dtype_of(kwargs.get("dtype"))
            ints = [_known_int(a) for a in args[:3]]
            if len(args) == 1 and ints[0] is not None:
                n: object = ints[0]
            elif len(args) >= 2 and ints[0] is not None and \
                    ints[1] is not None:
                step = ints[2] if len(args) > 2 and ints[2] else 1
                try:
                    n = max(0, -(-(ints[1] - ints[0]) // step))
                except Exception:
                    n = DYN
            else:
                n = DYN
            has_float = any(isinstance(a, Const) and
                            isinstance(a.value, float) for a in args[:3])
            out = Arr((n,), dt or (default_float if has_float else
                                   default_int))
            out.chain = extend_chain((), ln, f"arange -> {fmt_arr(out)}")
            return out
        if short == "linspace":
            n = _known_int(args[2] if len(args) > 2 else
                           kwargs.get("num", Const(50)))
            dt = self._dtype_of(kwargs.get("dtype")) or default_float
            out = Arr((n if n is not None else DYN,), dt)
            out.chain = extend_chain(
                (), ln, f"{'np' if numpy else 'jnp'}.linspace -> {fmt_arr(out)}")
            return out
        if short in ("eye", "identity"):
            n = _known_int(a0)
            m = _known_int(args[1]) if len(args) > 1 else n
            dt = self._dtype_of(kwargs.get("dtype")) or default_float
            return Arr((n if n is not None else DYN,
                        m if m is not None else DYN), dt)

        # manipulation
        if short == "reshape":
            return self._reshape(frame, args, kwargs, ln)
        if short == "ravel":
            if isinstance(a0, Arr):
                if a0.dims is not None and all(
                        isinstance(d, int) for d in a0.dims):
                    n = 1
                    for d in a0.dims:
                        n *= d
                    return Arr((n,), a0.dtype, chain=a0.chain)
                return Arr((DYN,), a0.dtype, chain=a0.chain)
            return UNKNOWN
        if short == "transpose":
            if not isinstance(a0, Arr):
                return UNKNOWN
            if a0.dims is None:
                return Arr(None, a0.dtype)
            perm = self._dims_of(args[1] if len(args) > 1 else
                                 kwargs.get("axes"))
            if perm is None:
                dims = tuple(reversed(a0.dims))
            elif all(isinstance(p, int) and -len(a0.dims) <= p <
                     len(a0.dims) for p in perm) and len(perm) == len(a0.dims):
                dims = tuple(a0.dims[p % len(a0.dims)] for p in perm)
            else:
                return Arr(None, a0.dtype)
            return Arr(dims, a0.dtype,
                       chain=extend_chain(a0.chain, ln,
                                          f"transpose -> {fmt_dims(dims)}"))
        if short in ("swapaxes", "moveaxis"):
            if not isinstance(a0, Arr) or a0.dims is None:
                return Arr(None, a0.dtype) if isinstance(a0, Arr) else UNKNOWN
            i = _known_int(args[1] if len(args) > 1 else None)
            j = _known_int(args[2] if len(args) > 2 else None)
            rank = len(a0.dims)
            if i is None or j is None or not (-rank <= i < rank) or \
                    not (-rank <= j < rank):
                return Arr(None, a0.dtype)
            dims = list(a0.dims)
            if short == "swapaxes":
                dims[i % rank], dims[j % rank] = dims[j % rank], dims[i % rank]
            else:
                d = dims.pop(i % rank)
                dims.insert(j % rank, d)
            return Arr(tuple(dims), a0.dtype, chain=a0.chain)
        if short == "expand_dims":
            if not isinstance(a0, Arr) or a0.dims is None:
                return Arr(None, a0.dtype) if isinstance(a0, Arr) else UNKNOWN
            k = _known_int(args[1] if len(args) > 1 else kwargs.get("axis"))
            if k is None:
                return Arr(None, a0.dtype)
            rank = len(a0.dims)
            if k < 0:
                k = rank + 1 + k
            k = max(0, min(k, rank))
            dims = a0.dims[:k] + (1,) + a0.dims[k:]
            return Arr(dims, a0.dtype, chain=a0.chain)
        if short == "squeeze":
            if not isinstance(a0, Arr) or a0.dims is None:
                return Arr(None, a0.dtype) if isinstance(a0, Arr) else UNKNOWN
            axis = self._axis_arg(args, kwargs)
            if axis is None:
                if all(isinstance(d, int) for d in a0.dims):
                    return Arr(tuple(d for d in a0.dims if d != 1),
                               a0.dtype, chain=a0.chain)
                return Arr(None, a0.dtype)
            axes = self._dims_of(axis)
            if axes is None or not all(isinstance(x, int) for x in axes):
                return Arr(None, a0.dtype)
            rank = len(a0.dims)
            drop = {x % rank for x in axes if -rank <= x < rank}
            return Arr(tuple(d for i, d in enumerate(a0.dims)
                             if i not in drop), a0.dtype, chain=a0.chain)
        if short == "broadcast_to":
            target = self._dims_of(args[1] if len(args) > 1 else
                                   kwargs.get("shape"))
            if not isinstance(a0, Arr):
                return Arr(target) if target is not None else UNKNOWN
            if target is None:
                return Arr(None, a0.dtype)
            if a0.dims is not None:
                src = list(a0.dims)
                if len(src) > len(target):
                    self._emit(
                        "shape-mismatch", frame, ln,
                        f"broadcast_to target rank {len(target)} is lower "
                        f"than input {fmt_arr(a0)}",
                        a0.chain,
                    )
                else:
                    for ds, dt_ in zip(reversed(src), reversed(target)):
                        if isinstance(ds, int) and isinstance(dt_, int) and \
                                ds != 1 and ds != dt_:
                            self._emit(
                                "shape-mismatch", frame, ln,
                                f"cannot broadcast {fmt_arr(a0)} to "
                                f"{fmt_dims(tuple(target))}: dim {ds} vs "
                                f"{dt_}",
                                a0.chain,
                            )
                            break
            return Arr(tuple(target), a0.dtype,
                       chain=extend_chain(a0.chain, ln,
                                          f"broadcast_to {fmt_dims(tuple(target))}"))
        if short in ("concatenate", "concat"):
            return self._concat(frame, args, kwargs, ln)
        if short in ("stack", "vstack", "hstack", "dstack", "column_stack"):
            if short != "stack":
                return UNKNOWN
            return self._stack(frame, args, kwargs, ln)
        if short == "pad":
            return self._pad(frame, args, kwargs, ln)
        if short == "where":
            if len(args) < 3:
                return UNKNOWN
            xy = self._broadcast_op(frame, args[1], args[2], ln, "where")
            if isinstance(xy, Arr) and isinstance(args[0], Arr):
                dims = self._broadcast_dims(frame, args[0].dims, xy.dims, ln,
                                            "where", xy.chain,
                                            args[0], xy)
                return Arr(dims, xy.dtype, chain=xy.chain)
            return xy
        if short == "repeat":
            if not isinstance(a0, Arr) or a0.dims is None:
                return Arr(None, a0.dtype) if isinstance(a0, Arr) else UNKNOWN
            reps = _known_int(args[1] if len(args) > 1 else
                              kwargs.get("repeats"))
            axis = _known_int(self._axis_arg(args, kwargs, pos=2))
            if axis is None:
                total = DYN
                if reps is not None and all(
                        isinstance(d, int) for d in a0.dims):
                    total = reps
                    for d in a0.dims:
                        total *= d
                return Arr((total,), a0.dtype, chain=a0.chain)
            rank = len(a0.dims)
            if not (-rank <= axis < rank):
                return Arr(None, a0.dtype)
            axis %= rank
            d = a0.dims[axis]
            newd = d * reps if isinstance(d, int) and reps is not None else DYN
            return Arr(a0.dims[:axis] + (newd,) + a0.dims[axis + 1:],
                       a0.dtype, chain=a0.chain)
        if short == "tile":
            return Arr(None, a0.dtype) if isinstance(a0, Arr) else UNKNOWN
        if short in ("split", "array_split", "unstack", "meshgrid",
                     "unique", "nonzero", "ix_", "indices", "histogram"):
            return UNKNOWN
        if short in ("take", "take_along_axis", "searchsorted", "digitize",
                     "interp", "bincount"):
            return UNKNOWN

        # contraction
        if short == "einsum":
            return self._einsum(frame, args, kwargs, ln)
        if short in ("matmul", "dot", "tensordot", "inner", "outer", "vdot"):
            if short in ("matmul", "dot") and len(args) >= 2:
                return self._matmul(frame, args[0], args[1], ln, kwargs)
            return UNKNOWN

        # elementwise / reductions
        if short in _BINARY_BROADCAST and len(args) >= 2:
            return self._broadcast_op(frame, args[0], args[1], ln, short)
        if short in _BINARY_BOOL and len(args) >= 2:
            return self._broadcast_op(frame, args[0], args[1], ln, short,
                                      bool_result=True)
        if short in _UNARY_BOOL:
            if isinstance(a0, Arr):
                return Arr(a0.dims, "bool", chain=a0.chain)
            return UNKNOWN
        if short in _UNARY_ELEMENTWISE:
            if isinstance(a0, Arr):
                dt = a0.dtype
                if short in _UNARY_FLOATING and dt is not None and not (
                        dt.startswith("float") or dt.startswith("bfloat") or
                        dt.startswith("complex")):
                    dt = default_float
                out = Arr(a0.dims, dt, a0.spec, a0.chain)
                self._check_promotion(frame, ln, out, (a0.dtype,), short)
                return out
            if isinstance(a0, Const) and isinstance(a0.value, (int, float)):
                import math as _math
                pyfn = {"sqrt": _math.sqrt, "exp": _math.exp,
                        "log": _math.log, "abs": abs,
                        "floor": _math.floor, "ceil": _math.ceil}.get(short)
                if pyfn is not None:
                    try:
                        return Const(pyfn(a0.value))
                    except Exception:
                        return UNKNOWN
                return UNKNOWN
            return UNKNOWN
        if short in _REDUCTIONS:
            if not isinstance(a0, Arr):
                return UNKNOWN
            dims = self._reduce_dims(a0, self._axis_arg(args, kwargs),
                                     kwargs.get("keepdims"))
            if short in _REDUCTION_INT_RESULT:
                dt: Optional[str] = default_int
            elif short in _REDUCTION_BOOL_RESULT:
                dt = "bool"
            elif short in ("mean", "std", "var", "nanmean", "nanstd",
                           "nanvar", "median", "nanmedian") and \
                    a0.dtype is not None and not (
                        a0.dtype.startswith("float") or
                        a0.dtype.startswith("bfloat")):
                dt = default_float
            else:
                dt = a0.dtype
            return Arr(dims, dt,
                       chain=extend_chain(a0.chain, ln,
                                          f"{short} -> {fmt_dims(dims)}"))
        if short in _SAME_SHAPE:
            if isinstance(a0, Arr):
                dt = default_int if short == "argsort" else a0.dtype
                return Arr(a0.dims, dt, a0.spec, a0.chain)
            return UNKNOWN
        if short == "astype" and len(args) >= 2:
            dt = self._dtype_of(args[1])
            if isinstance(a0, Arr) and dt:
                return self._cast(frame, a0, dt, ln)
            return UNKNOWN
        if short in _DTYPE_NAMES:
            # jnp.float32(x) — cast call on the dtype object
            if args:
                return self._cast(frame, a0, short, ln)
            return DtypeVal(short)
        return UNKNOWN

    def _reshape(self, frame: _Frame, args: List[object],
                 kwargs: Dict[str, object], ln: int) -> object:
        a0 = args[0] if args else None
        if not isinstance(a0, Arr):
            return UNKNOWN
        rest = args[1:]
        if len(rest) == 1:
            target = self._dims_of(rest[0])
            if target is None:
                target_list = [_val_to_dim(rest[0])]
            else:
                target_list = list(target)
        elif "newshape" in kwargs or "shape" in kwargs:
            target = self._dims_of(kwargs.get("newshape",
                                              kwargs.get("shape")))
            target_list = list(target) if target is not None else [DYN]
        else:
            target_list = [_val_to_dim(v) for v in rest]
        if not target_list:
            target_list = []
        neg = [i for i, d in enumerate(target_list)
               if isinstance(d, int) and d == -1]
        known_new = [d for d in target_list if isinstance(d, int) and d != -1]
        all_new_int = all(isinstance(d, int) for d in target_list)
        orig_n: Optional[int] = None
        if a0.dims is not None and all(isinstance(d, int) for d in a0.dims):
            orig_n = 1
            for d in a0.dims:
                orig_n *= d
        if len(neg) == 1 and all(
                isinstance(d, int) for d in target_list if d != -1):
            rest_n = 1
            for d in known_new:
                rest_n *= d
            if orig_n is not None:
                if rest_n == 0 or orig_n % rest_n != 0:
                    self._emit(
                        "shape-mismatch", frame, ln,
                        f"reshape of {fmt_arr(a0)} to "
                        f"{fmt_dims(tuple(target_list))} does not preserve "
                        f"the element count ({orig_n} elements)",
                        a0.chain,
                    )
                    target_list[neg[0]] = DYN
                else:
                    target_list[neg[0]] = orig_n // rest_n
            else:
                target_list[neg[0]] = DYN
        elif not neg and all_new_int and orig_n is not None:
            new_n = 1
            for d in target_list:
                new_n *= d
            if new_n != orig_n:
                self._emit(
                    "shape-mismatch", frame, ln,
                    f"reshape of {fmt_arr(a0)} to "
                    f"{fmt_dims(tuple(target_list))} changes the element "
                    f"count ({orig_n} -> {new_n})",
                    a0.chain,
                )
        elif neg:
            for i in neg:
                target_list[i] = DYN
        dims = tuple(target_list)
        return Arr(dims, a0.dtype, None,
                   extend_chain(a0.chain, ln, f"reshape -> {fmt_dims(dims)}"))

    def _concat(self, frame: _Frame, args: List[object],
                kwargs: Dict[str, object], ln: int) -> object:
        seq = args[0] if args else None
        if not isinstance(seq, TupVal) or not seq.items:
            return UNKNOWN
        axis = _known_int(self._axis_arg(args, kwargs)) or 0
        arrs = [v for v in seq.items if isinstance(v, Arr)]
        if len(arrs) != len(seq.items):
            return UNKNOWN
        if any(a.dims is None for a in arrs):
            return Arr(None, arrs[0].dtype)
        rank = len(arrs[0].dims)
        if any(len(a.dims) != rank for a in arrs) or not (
                -rank <= axis < rank):
            self._emit(
                "shape-mismatch", frame, ln,
                "concatenate operands have different ranks: " +
                ", ".join(fmt_arr(a) for a in arrs),
                arrs[0].chain,
            )
            return Arr(None, arrs[0].dtype)
        axis %= rank
        out_dims: List[object] = []
        for i in range(rank):
            ds = [a.dims[i] for a in arrs]
            if i == axis:
                if all(isinstance(d, int) for d in ds):
                    out_dims.append(sum(ds))
                else:
                    out_dims.append(DYN)
                continue
            ints = [d for d in ds if isinstance(d, int)]
            if len(set(ints)) > 1:
                self._emit(
                    "shape-mismatch", frame, ln,
                    f"concatenate along axis {axis}: operands disagree on "
                    f"dim {i}: " + ", ".join(fmt_arr(a) for a in arrs),
                    arrs[0].chain,
                )
                out_dims.append(DYN)
            elif ints and len(ints) == len(ds):
                out_dims.append(ints[0])
            elif len({id(d) if isinstance(d, Sym) else d
                      for d in ds}) == 1:
                out_dims.append(ds[0])
            else:
                out_dims.append(ints[0] if ints else DYN)
        dt = arrs[0].dtype
        for a in arrs[1:]:
            dt = promote_dtype(dt, a.dtype)
        dims = tuple(out_dims)
        return Arr(dims, dt,
                   chain=extend_chain(arrs[0].chain, ln,
                                      f"concatenate -> {fmt_dims(dims)}"))

    def _stack(self, frame: _Frame, args: List[object],
               kwargs: Dict[str, object], ln: int) -> object:
        seq = args[0] if args else None
        if not isinstance(seq, TupVal) or not seq.items:
            return UNKNOWN
        axis = _known_int(self._axis_arg(args, kwargs)) or 0
        arrs = [v for v in seq.items if isinstance(v, Arr)]
        if len(arrs) != len(seq.items):
            return UNKNOWN
        if any(a.dims is None for a in arrs):
            return Arr(None, arrs[0].dtype)
        rank = len(arrs[0].dims)
        for a in arrs[1:]:
            if len(a.dims) != rank:
                self._emit(
                    "shape-mismatch", frame, ln,
                    "stack operands have different ranks: " +
                    ", ".join(fmt_arr(x) for x in arrs),
                    arrs[0].chain,
                )
                return Arr(None, arrs[0].dtype)
            for i in range(rank):
                d0, d1 = arrs[0].dims[i], a.dims[i]
                if isinstance(d0, int) and isinstance(d1, int) and d0 != d1:
                    self._emit(
                        "shape-mismatch", frame, ln,
                        f"stack operands disagree on dim {i}: "
                        f"{fmt_arr(arrs[0])} vs {fmt_arr(a)}",
                        arrs[0].chain,
                    )
                    return Arr(None, arrs[0].dtype)
        if not (-rank - 1 <= axis <= rank):
            return Arr(None, arrs[0].dtype)
        if axis < 0:
            axis = rank + 1 + axis
        dims = arrs[0].dims[:axis] + (len(arrs),) + arrs[0].dims[axis:]
        dt = arrs[0].dtype
        for a in arrs[1:]:
            dt = promote_dtype(dt, a.dtype)
        return Arr(dims, dt,
                   chain=extend_chain(arrs[0].chain, ln,
                                      f"stack -> {fmt_dims(dims)}"))

    def _pad(self, frame: _Frame, args: List[object],
             kwargs: Dict[str, object], ln: int) -> object:
        a0 = args[0] if args else None
        if not isinstance(a0, Arr):
            return UNKNOWN
        if a0.dims is None:
            return Arr(None, a0.dtype)
        width = args[1] if len(args) > 1 else kwargs.get("pad_width")
        rank = len(a0.dims)
        dims = list(a0.dims)

        def add(d: object, lo: object, hi: object) -> object:
            l, h = _known_int(lo), _known_int(hi)
            if isinstance(d, int) and l is not None and h is not None:
                return d + l + h
            return DYN

        if isinstance(width, Const) and isinstance(width.value, int):
            dims = [add(d, width, width) for d in dims]
        elif isinstance(width, TupVal):
            if len(width.items) == 2 and all(
                    not isinstance(i, TupVal) for i in width.items):
                lo, hi = width.items
                dims = [add(d, lo, hi) for d in dims]
            elif len(width.items) == rank:
                for i, pair in enumerate(width.items):
                    if isinstance(pair, TupVal) and len(pair.items) == 2:
                        dims[i] = add(dims[i], pair.items[0], pair.items[1])
                    else:
                        dims[i] = DYN
            else:
                dims = [DYN] * rank
        else:
            dims = [DYN] * rank
        new = tuple(dims)
        return Arr(new, a0.dtype,
                   chain=extend_chain(a0.chain, ln,
                                      f"pad -> {fmt_dims(new)}"))


# --------------------------------------------------------------------------
# per-run cache (same identity discipline as ``project_graph``)
# --------------------------------------------------------------------------

_LAST_SHAPES: Optional[Tuple[Tuple[int, ...], "ProjectShapes"]] = None


def project_shapes(modules: Sequence[ModuleInfo]) -> ProjectShapes:
    """The shared per-run interpreter result for a module list.

    Keyed on module identity so the four shape rules run one analysis
    between them, mirroring ``project_graph``.
    """
    global _LAST_SHAPES
    key = tuple(id(m) for m in modules)
    if _LAST_SHAPES is not None and _LAST_SHAPES[0] == key:
        return _LAST_SHAPES[1]
    shapes = ProjectShapes(modules)
    _LAST_SHAPES = (key, shapes)
    return shapes

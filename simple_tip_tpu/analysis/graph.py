"""tiplint project graph: whole-program import/call/sharding index.

The per-file rules (``rules/common.py``) are deliberately local — they see
one module at a time. The defect classes that actually sink pjit/shard_map
programs are inherently cross-module: a ``PartitionSpec`` naming an axis no
mesh constructs, an impure helper reached *through* a call chain into a
jitted function defined elsewhere, a concrete-shape assumption in a kernel
traced from another file. This module builds the whole-program picture the
graph-backed rules (``sharding_spec``, ``transitive_purity``) reason over:

- **module naming**: every analyzed file gets a canonical dotted module name
  (a root directory containing ``__init__.py`` contributes its basename as
  the package prefix, so ``simple_tip_tpu/parallel/ensemble.py`` under the
  package root is ``simple_tip_tpu.parallel.ensemble`` — exactly what its
  absolute imports say);
- **function index**: module- and class-level defs, addressable by dotted
  name, so an import alias resolves to the function object it names;
- **call graph**: for any function body, the resolvable intra-project call
  edges (bare local names, imported names, ``mod.fn`` attribute chains and
  ``functools.partial(f, ...)`` wrappers);
- **trace boundaries**: every ``jit``/``pjit``/``vmap``/``shard_map``/
  ``pallas_call`` call site together with the project function it traces
  (resolved through partial wrappers and local bindings), which is how a
  function with no local jit marker is discovered to be device code because
  *another module* shard_maps it;
- **sharding index**: every ``Mesh(...)``/``jax.make_mesh(...)`` site with
  its axis-name tuple, and every ``PartitionSpec(...)`` literal with its
  axis-name strings — string constants resolve through module-level
  ``NAME = "axis"`` assignments and cross-module imports of them.

Everything here is stdlib-``ast`` (the analyzer must run without jax
installed) and intentionally syntactic: resolution is best-effort, and every
consumer treats "unresolved" as "unknown", never as "safe" or "unsafe".
"""

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from simple_tip_tpu.analysis.core import ModuleInfo
from simple_tip_tpu.analysis.rules.common import (
    FunctionNode,
    TRANSFORM_CALLEES,
    callable_targets,
    callee_name,
    dotted,
    function_body_nodes,
    import_aliases,
    jit_reachable_functions,
    name_bindings,
)

#: Callees that construct a device mesh; the axis-name tuple is the second
#: positional argument or the ``axis_names`` keyword.
MESH_CALLEES = {
    "jax.sharding.Mesh",
    "jax.experimental.maps.Mesh",
    "jax.interpreters.pxla.Mesh",
    "jax.make_mesh",
    "jax.sharding.make_mesh",
}

#: Callees that construct a PartitionSpec (positional args are axis names).
PARTITION_SPEC_CALLEES = {
    "jax.sharding.PartitionSpec",
    "jax.experimental.pjit.PartitionSpec",
    "jax.interpreters.pxla.PartitionSpec",
    "jax.P",
}


@dataclass(eq=False)
class FunctionInfo:
    """One module- or class-level function definition in the project."""

    module: ModuleInfo
    qualname: str  # "fn" or "Class.fn"
    node: FunctionNode
    dotted: str  # "<module dotted name>.<qualname>"

    @property
    def line(self) -> int:
        """Definition line of the function."""
        return self.node.lineno


@dataclass(eq=False)
class MeshSite:
    """One ``Mesh(...)`` construction and the axis names it declares."""

    module: ModuleInfo
    line: int
    axes: Tuple[str, ...]  # the resolved axis-name strings
    complete: bool  # False when some axis expression did not resolve


@dataclass(eq=False)
class SpecSite:
    """One ``PartitionSpec(...)`` literal and its resolved axis names."""

    module: ModuleInfo
    line: int
    axes: Tuple[str, ...]  # resolved string axes only (None entries dropped)


@dataclass(eq=False)
class Boundary:
    """One trace boundary: a transform call and the function it traces."""

    module: ModuleInfo
    line: int
    transform: str  # canonical dotted transform name (jax.shard_map, ...)
    target: Optional["FunctionInfo"]  # None when the callee didn't resolve


@dataclass(eq=False)
class _ModuleIndex:
    """Per-module resolution state the graph builds once."""

    info: ModuleInfo
    name: str  # dotted module name
    aliases: Dict[str, str] = field(default_factory=dict)
    bindings: Dict[str, List[ast.expr]] = field(default_factory=dict)
    constants: Dict[str, str] = field(default_factory=dict)  # NAME -> "str"
    defs: Dict[str, FunctionInfo] = field(default_factory=dict)  # by qualname
    jit_local: Set[FunctionNode] = field(default_factory=set)
    # id(method node) -> enclosing class name, for self.method() resolution
    method_class: Dict[int, str] = field(default_factory=dict)


def module_dotted_name(module: ModuleInfo, package_roots: Set[str]) -> str:
    """Canonical dotted name of ``module`` (see the module docstring)."""
    parts = module.relpath[:-3].split("/")  # strip ".py"
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if module.root in package_roots:
        parts = [os.path.basename(module.root)] + parts
    return ".".join(parts)


class ProjectGraph:
    """Whole-program index over one ``analyze_paths`` module set.

    Build once per run with :meth:`build` (package rules share a single
    instance via :func:`project_graph`, keyed on the module list identity,
    so the three graph-backed rules don't triplicate the work).
    """

    def __init__(self, modules: Sequence[ModuleInfo]):
        package_roots = {
            m.root for m in modules if m.relpath == "__init__.py"
        }
        self._by_module: Dict[int, _ModuleIndex] = {}
        self._by_name: Dict[str, _ModuleIndex] = {}
        self.functions: Dict[str, FunctionInfo] = {}  # by dotted name
        self.meshes: List[MeshSite] = []
        self.specs: List[SpecSite] = []
        self.boundaries: List[Boundary] = []

        for m in modules:
            idx = _ModuleIndex(info=m, name=module_dotted_name(m, package_roots))
            idx.aliases = import_aliases(m.tree)
            self._augment_relative_imports(idx)
            idx.bindings = name_bindings(m.tree)
            idx.constants = _module_constants(m.tree)
            self._by_module[id(m)] = idx
            self._by_name[idx.name] = idx
            for qualname, node in _iter_defs(m.tree):
                fi = FunctionInfo(
                    module=m,
                    qualname=qualname,
                    node=node,
                    dotted=f"{idx.name}.{qualname}" if idx.name else qualname,
                )
                idx.defs.setdefault(qualname, fi)
                self.functions.setdefault(fi.dotted, fi)
                if "." in qualname:
                    idx.method_class[id(node)] = qualname.split(".")[0]

        # Second pass: needs the full function index for target resolution.
        for m in modules:
            idx = self._by_module[id(m)]
            idx.jit_local = jit_reachable_functions(m.tree, idx.aliases)
            self._index_sharding(idx)
            self._index_boundaries(idx)

    # -- construction helpers -------------------------------------------------

    @staticmethod
    def _augment_relative_imports(idx: _ModuleIndex) -> None:
        """Resolve ``from . import x`` / ``from ..pkg import y`` aliases
        (skipped by ``import_aliases``) against the module's own package."""
        pkg_parts = idx.name.split(".")[:-1] if idx.name else []
        for node in ast.walk(idx.info.tree):
            if not (isinstance(node, ast.ImportFrom) and node.level > 0):
                continue
            base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
            if node.level - 1 > len(pkg_parts):
                continue  # escapes the analyzed tree
            prefix = ".".join(base + ([node.module] if node.module else []))
            for a in node.names:
                if prefix:
                    idx.aliases.setdefault(
                        a.asname or a.name, f"{prefix}.{a.name}"
                    )

    def _index_sharding(self, idx: _ModuleIndex) -> None:
        for node in ast.walk(idx.info.tree):
            if not isinstance(node, ast.Call):
                continue
            name = callee_name(node, idx.aliases)
            if name in MESH_CALLEES:
                axes_node = None
                if len(node.args) >= 2:
                    axes_node = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes_node = kw.value
                axes, complete = self._resolve_axes(idx, axes_node)
                self.meshes.append(
                    MeshSite(
                        module=idx.info,
                        line=node.lineno,
                        axes=tuple(axes),
                        complete=complete,
                    )
                )
            elif name in PARTITION_SPEC_CALLEES:
                axes: List[str] = []
                elements: List[ast.AST] = []
                for arg in node.args:
                    if isinstance(arg, (ast.Tuple, ast.List)):
                        elements.extend(arg.elts)
                    else:
                        elements.append(arg)
                for el in elements:
                    s = self.resolve_string(idx.info, el)
                    if s is not None:
                        axes.append(s)
                self.specs.append(
                    SpecSite(module=idx.info, line=node.lineno, axes=tuple(axes))
                )

    def _resolve_axes(
        self, idx: _ModuleIndex, axes_node: Optional[ast.AST]
    ) -> Tuple[List[str], bool]:
        if axes_node is None:
            return [], False
        elements: List[ast.AST]
        if isinstance(axes_node, (ast.Tuple, ast.List)):
            elements = list(axes_node.elts)
        else:
            elements = [axes_node]
        axes: List[str] = []
        complete = True
        for el in elements:
            s = self.resolve_string(idx.info, el)
            if s is None:
                complete = False
            else:
                axes.append(s)
        return axes, complete

    def _index_boundaries(self, idx: _ModuleIndex) -> None:
        # Decorator boundaries: @jax.jit / @partial(jax.jit, ...) on a def.
        for fi in idx.defs.values():
            decorators = getattr(fi.node, "decorator_list", [])
            for d in decorators:
                transform = _decorator_transform(d, idx.aliases)
                if transform is not None:
                    self.boundaries.append(
                        Boundary(
                            module=idx.info, line=fi.node.lineno,
                            transform=transform, target=fi,
                        )
                    )
        # Call boundaries: jax.jit(f), jax.shard_map(partial(f, ...), ...).
        for node in ast.walk(idx.info.tree):
            if not isinstance(node, ast.Call):
                continue
            name = callee_name(node, idx.aliases)
            if name not in TRANSFORM_CALLEES or not node.args:
                continue
            targets, _lambdas = callable_targets(
                node.args[0], idx.aliases, idx.bindings
            )
            resolved = [
                fi
                for fi in (
                    self.resolve_function(idx.info, t) for t in sorted(targets)
                )
                if fi is not None
            ]
            if resolved:
                for fi in resolved:
                    self.boundaries.append(
                        Boundary(
                            module=idx.info, line=node.lineno,
                            transform=name, target=fi,
                        )
                    )
            else:
                self.boundaries.append(
                    Boundary(
                        module=idx.info, line=node.lineno,
                        transform=name, target=None,
                    )
                )

    # -- queries --------------------------------------------------------------

    def module_name(self, module: ModuleInfo) -> str:
        """Dotted module name of an analyzed module."""
        return self._by_module[id(module)].name

    def jit_reachable(self, module: ModuleInfo) -> Set[FunctionNode]:
        """The module's locally jit-reachable function nodes (cached)."""
        return self._by_module[id(module)].jit_local

    def aliases(self, module: ModuleInfo) -> Dict[str, str]:
        """The module's import-alias table (``jnp`` -> ``jax.numpy``)."""
        return self._by_module[id(module)].aliases

    def resolve_string(
        self, module: ModuleInfo, node: ast.AST, _depth: int = 0
    ) -> Optional[str]:
        """A string literal, or a Name/Attribute resolving (possibly through
        imports) to a module-level ``NAME = "str"`` constant; else None."""
        if isinstance(node, ast.Constant):
            return node.value if isinstance(node.value, str) else None
        if _depth > 4:
            return None
        idx = self._by_module[id(module)]
        name = dotted(node, idx.aliases) if isinstance(
            node, (ast.Name, ast.Attribute)
        ) else None
        if name is None:
            return None
        if "." not in name:
            return idx.constants.get(name)
        if name in self._by_name:
            return None  # the name denotes a module, not a constant
        owner, attr = name.rsplit(".", 1)
        target = self._by_name.get(owner)
        if target is not None:
            return target.constants.get(attr)
        return None

    def resolve_function(
        self, module: ModuleInfo, name: str
    ) -> Optional[FunctionInfo]:
        """The FunctionInfo a (possibly dotted, alias-resolved) name denotes
        from ``module``'s point of view, or None."""
        if not name:
            return None
        idx = self._by_module[id(module)]
        if "." not in name:
            return idx.defs.get(name)
        # Module-local "Class.meth" qualname (the self-call resolution path).
        local = idx.defs.get(name)
        if local is not None:
            return local
        # Fully-qualified: "pkg.mod.fn" or "pkg.mod.Class.fn".
        fi = self.functions.get(name)
        if fi is not None:
            return fi
        # "modalias.fn" where the alias maps to a module dotted name.
        owner, attr = name.rsplit(".", 1)
        target = self._by_name.get(owner)
        if target is not None:
            return target.defs.get(attr)
        return None

    def calls_from(
        self, module: ModuleInfo, fn: FunctionNode
    ) -> Iterator[Tuple[ast.Call, FunctionInfo]]:
        """Resolvable project-internal call edges out of ``fn``'s body.

        Covers direct calls (``helper(...)``, ``mod.helper(...)``),
        ``functools.partial(helper, ...)`` references — a partial built
        inside traced code executes its target under the same trace — and
        ``self.method(...)`` calls, resolved against the enclosing class
        of ``fn`` when ``fn`` is one of its methods.
        """
        idx = self._by_module[id(module)]
        own_class = idx.method_class.get(id(fn))
        seen: Set[Tuple[int, int]] = set()
        for node in function_body_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            name = callee_name(node, idx.aliases)
            candidates: Set[str] = set()
            if name is not None and name not in TRANSFORM_CALLEES:
                candidates.add(name)
            if (
                own_class is not None
                and name is not None
                and name.startswith("self.")
                and name.count(".") == 1
            ):
                candidates.add(f"{own_class}.{name[len('self.'):]}")
            if name in ("functools.partial", "partial") and node.args:
                sub, _ = callable_targets(node.args[0], idx.aliases, idx.bindings)
                candidates = sub
            for cand in sorted(candidates):
                fi = self.resolve_function(module, cand)
                if fi is None or fi.node is fn:
                    continue
                key = (node.lineno, id(fi))
                if key in seen:
                    continue
                seen.add(key)
                yield node, fi

    def traced_entries(self) -> Iterator[Tuple[FunctionInfo, Optional[Boundary]]]:
        """Every project function known to execute as traced device code.

        Yields ``(function, boundary)`` pairs: boundary is None for
        functions locally jit-reachable in their own module, and the
        cross-module trace site (e.g. the shard_map call in another file)
        otherwise.
        """
        emitted: Set[int] = set()
        for idx in self._by_module.values():
            for fi in idx.defs.values():
                if fi.node in idx.jit_local and id(fi) not in emitted:
                    emitted.add(id(fi))
                    yield fi, None
        for b in self.boundaries:
            if b.target is not None and id(b.target) not in emitted:
                emitted.add(id(b.target))
                yield b.target, b


def _decorator_transform(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """The canonical transform name a decorator applies, or None.

    ``@jax.jit`` -> ``jax.jit``; ``@partial(jax.jit, ...)`` and
    ``@jax.jit(static_argnames=...)`` both -> ``jax.jit``.
    """
    name = dotted(node, aliases)
    if name in TRANSFORM_CALLEES:
        return name
    if isinstance(node, ast.Call):
        inner = callee_name(node, aliases)
        if inner in TRANSFORM_CALLEES:
            return inner
        if inner in ("functools.partial", "partial") and node.args:
            first = dotted(node.args[0], aliases)
            if first in TRANSFORM_CALLEES:
                return first
    return None


def _module_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "string"`` (and annotated) assignments."""
    constants: Dict[str, str] = {}
    for node in tree.body:
        value = None
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        if not (isinstance(value, ast.Constant) and isinstance(value.value, str)):
            continue
        for target in targets:
            if isinstance(target, ast.Name):
                constants.setdefault(target.id, value.value)
    return constants


def _iter_defs(tree: ast.Module) -> Iterator[Tuple[str, FunctionNode]]:
    """(qualname, node) for module-level defs and class methods."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub


#: (module list, graph) of the most recent build. Identity-compared (a
#: strong reference, so the list's id can never be recycled underneath us).
_LAST_GRAPH: Optional[Tuple[Sequence[ModuleInfo], ProjectGraph]] = None


def project_graph(modules: Sequence[ModuleInfo]) -> ProjectGraph:
    """The (per-run cached) ProjectGraph for a module set.

    ``analyze_paths`` hands every package rule the same list object, so
    caching on its identity means the graph is built once per run no matter
    how many graph-backed rules are registered. Only the latest module set
    is kept — an analyzer run is single-threaded and sequential.
    """
    global _LAST_GRAPH
    if _LAST_GRAPH is None or _LAST_GRAPH[0] is not modules:
        _LAST_GRAPH = (modules, ProjectGraph(modules))
    return _LAST_GRAPH[1]

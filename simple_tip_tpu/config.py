"""Global configuration: the filesystem artifact bus.

The reference hardcodes ``OUTPUT_FOLDER = "/assets/"``
(reference: src/dnn_test_prio/case_study.py:10) as a mounted volume; here the
root is configurable via the ``TIP_ASSETS`` environment variable (default
``./assets``) and all artifact paths are constructed through helpers so the
*naming contract* — which the result aggregation layer parses by splitting on
underscores — lives in exactly one place.

The bus layout (SURVEY.md section 1, "storage bus"):

- ``priorities/{cs}_{ds}_{model}_{type}.npy``   scores / orders / masks
- ``times/{cs}_{ds}_{model}_{metric}``          pickled [setup, pred, quant, cam]
- ``active_learning/{cs}_{model}_{metric}_{oodnom}.pickle``
- ``models/{cs}/``                              per-run checkpoints
- ``results/``                                  tables and plots
- ``activations/{cs}/model_{id}/{ds}/layer_{i}/badge_{j}.npy``
"""

import os


def output_folder() -> str:
    """Root of the filesystem artifact bus."""
    return os.environ.get("TIP_ASSETS", os.path.join(os.getcwd(), "assets"))


def data_folder() -> str:
    """Directory with raw dataset files (npy/npz caches)."""
    return os.environ.get("TIP_DATA_DIR", os.path.join(os.getcwd(), "datasets"))


def subdir(name: str) -> str:
    """Path of (and ensure) an artifact-bus subdirectory."""
    path = os.path.join(output_folder(), name)
    os.makedirs(path, exist_ok=True)
    return path


def enable_compilation_cache() -> None:
    """Turn on JAX's persistent compilation cache (idempotent).

    The experiment phases re-launch the same XLA programs across runs and
    process restarts (the phases are restartable by design, SURVEY.md section
    5 checkpoint/resume); caching compiled executables under ``TIP_JAX_CACHE``
    (default ``./.jax_cache``) removes recompiles on every entry point.
    Disable with ``TIP_JAX_CACHE=off``.
    """
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cache = os.environ.get("TIP_JAX_CACHE", os.path.join(repo_root, ".jax_cache"))
    if cache.lower() == "off":
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", cache)
    # Cache EVERY program: the prio phase dispatches ~100 small XLA programs
    # whose compiles are individually fast (~0.1s) but recompile on every
    # run/restart — profiled at 10.3s of a 22.5s warm tiny-run with the
    # default 1s (here 0.5s) threshold, all cache misses.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


MAX_NUM_MODELS = 100


def scoring_compute_dtype():
    """Compute dtype for the *scoring* forward passes (prioritization and
    active-learning selection), from ``TIP_COMPUTE_DTYPE``.

    ``bfloat16`` runs model compute MXU-native (parameters, softmax and taps
    stay f32 — see models/convnet.py); unset or ``float32`` keeps the exact
    f32-parity path. Training always runs f32 regardless, so checkpoints and
    the reference's training-distribution parity are unaffected.
    """
    value = os.environ.get("TIP_COMPUTE_DTYPE", "").strip().lower()
    if value in ("", "float32", "f32"):
        return None
    if value in ("bfloat16", "bf16"):
        return "bfloat16"
    raise ValueError(
        f"TIP_COMPUTE_DTYPE={value!r} not understood; use 'float32' or 'bfloat16'"
    )

"""Knob-space search against the learned cost model (the planner core).

"A Learned Performance Model for TPUs" (arxiv 2008.01040) uses its model
the way this module does: score candidate configurations against
*predicted* cost and pick, instead of burning device hours per candidate.
The search is deterministic coordinate descent over the typed knob space
(``plan/knobs.py``), scoring every candidate with the SAME
``costmodel.fit`` + ``predict_study`` arithmetic ``obs predict`` quotes —
a plan's stored per-phase seconds are exactly what the CLI would print
for that configuration, by construction.

Two honesty rules, both load-bearing:

- **memory is a constraint, not a cost term**: a memory-capacity bound
  (``capacity_bytes``) is checked against a peak-bytes model fit from the
  feature store's ``device_peak_bytes`` rows; a candidate predicted over
  capacity is REJECTED outright — an OOM is not "slow", it is a dead
  study, so no predicted speedup may buy it back;
- **insufficient corpus fails LOUDLY**: an empty corpus, a corpus where
  no requested phase has any estimate, or a capacity bound without enough
  ``device_peak_bytes`` rows to fit the peak model all raise
  :class:`InsufficientCorpus` — the CLI maps it to the established exit-3
  contract. The planner never silently guesses.

Stdlib-only, like everything the tier-0 CI gate runs.
"""

from simple_tip_tpu.obs import costmodel
from simple_tip_tpu.plan import knobs as knobs_mod

#: Coordinate-descent pass bound: the space is small and scores are
#: deterministic, so a fixed point lands in 2-3 passes; this is a fuse.
MAX_PASSES = 4


class InsufficientCorpus(RuntimeError):
    """The corpus cannot support the requested plan (CLI exit 3)."""


class InfeasiblePlan(RuntimeError):
    """Every candidate violates the memory capacity bound (CLI exit 2)."""


def fit_memory_model(rows, min_rows: int = costmodel.DEFAULT_MIN_ROWS):
    """Peak-bytes model (``peak ~ a + b*batch + c*(group-1)``) from rows.

    Trains on non-degraded rows carrying both ``device_peak_bytes`` and
    ``batch``; a row's ``group`` (cross-run dispatch-fusion group size,
    absent on pre-group corpora) enters as ``group - 1`` so the ungrouped
    baseline contributes zero and a corpus with no grouped rows fits the
    exact pre-group model (the ridge pins the dead column to ~0). The
    ``c`` coefficient is the *measured* stacked-weights residency per
    extra group member — learned from telemetry, not computed from param
    counts, so it prices whatever the runtime actually holds resident.
    Returns ``{coef, n, max_peak_bytes}`` or None when fewer than
    ``min_rows`` rows qualify — the caller decides whether None is fatal
    (it is, whenever a capacity bound was requested).
    """
    obs = []
    for row in rows:
        peak = row.get("device_peak_bytes")
        batch = row.get("batch")
        if row.get("degraded") is True:
            continue
        if isinstance(peak, (int, float)) and isinstance(batch, (int, float)):
            group = row.get("group")
            g = float(group) if isinstance(group, (int, float)) else 1.0
            obs.append((float(batch), max(g, 1.0), float(peak)))
    if len(obs) < min_rows:
        return None
    try:
        coef = costmodel._least_squares(
            [[1.0, b, g - 1.0] for b, g, _p in obs],
            [p for _b, _g, p in obs],
        )
    except ValueError:
        return None
    return {
        "coef": [round(c, 6) for c in coef],
        "n": len(obs),
        "max_peak_bytes": int(max(p for _b, _g, p in obs)),
    }


def predict_peak_bytes(mem_model: dict, batch, group=1) -> int:
    """Predicted device peak bytes at ``(batch, group)`` under the model.

    A non-increasing batch fit (noise, constant-batch corpus) falls back
    to the max observed peak — constant but conservative, never
    extrapolating a negative slope into "bigger batches are free". The
    group term is additive ON TOP of that base and only applied when its
    learned coefficient is positive: a noisy negative ``c`` must never
    let a bigger G *discount* the predicted peak below the ungrouped
    baseline, because an over-capacity G is a dead study, not a slow one.
    """
    coef = mem_model["coef"]
    a, b = coef[0], coef[1]
    c = coef[2] if len(coef) > 2 else 0.0
    if b <= 0 or batch is None:
        base = float(mem_model["max_peak_bytes"])
    else:
        base = max(a + b * float(batch), 0.0)
    extra = 0.0
    if c > 0:
        extra = c * (max(float(group or 1), 1.0) - 1.0)
    return int(base + extra)


def search(rows, phases, runs: int, case_studies: int = 1, platform=None,
           capacity_bytes=None, pinned=None,
           min_rows: int = costmodel.DEFAULT_MIN_ROWS) -> dict:
    """Pick the knob assignment minimizing predicted study wall-clock.

    Returns the material ``plan.build`` needs: ``{assignment, predicted,
    memory, search}``. Raises :class:`InsufficientCorpus` (exit 3) or
    :class:`InfeasiblePlan` (exit 2) instead of guessing.
    """
    pinned = knobs_mod.validate_assignment(pinned or {})
    phases = list(phases)
    model = costmodel.fit(rows, min_rows)
    mem_model = None
    if capacity_bytes is not None:
        mem_model = fit_memory_model(rows, min_rows)
        if mem_model is None:
            raise InsufficientCorpus(
                f"memory capacity bound given, but the corpus has fewer "
                f"than {min_rows} non-degraded rows carrying both "
                f"device_peak_bytes and batch — cannot fit the peak-bytes "
                f"model, refusing to guess (grow the index with "
                f"`python -m simple_tip_tpu.obs runs`)"
            )

    def score(assignment):
        """``(predict_study result, peak_bytes, rejected)`` of a candidate."""
        params = knobs_mod.prediction_params(assignment, platform)
        pred = costmodel.predict_study(
            model, phases, runs, case_studies,
            platform=params["platform"], workers=params["workers"],
            batch=params["batch"], group=params.get("group"),
        )
        peak = None
        rejected = False
        if mem_model is not None:
            peak = predict_peak_bytes(
                mem_model, params["batch"], params.get("group") or 1
            )
            rejected = peak > capacity_bytes
        return pred, peak, rejected

    assignment = knobs_mod.default_assignment()
    assignment.update(pinned)
    base_pred, _peak, _rej = score(assignment)
    if not base_pred["ok"]:
        raise InsufficientCorpus(
            "no requested phase has any corpus estimate "
            f"(phases: {', '.join(phases)}; corpus rows used: "
            f"{model['rows_used']}) — refusing to plan from nothing"
        )

    evaluated = rejected_memory = passes = 0
    for _ in range(MAX_PASSES):
        passes += 1
        changed = False
        for k in knobs_mod.all_knobs():
            if k.name in pinned:
                continue
            # Seed with the CURRENT value (if feasible): a value only
            # replaces it when strictly better, so ties keep the knob's
            # default and knobs the model cannot distinguish never move —
            # the walk stays deterministic and `explain` says so honestly.
            cur_pred, _peak, cur_rej = score(assignment)
            best_value, best_total = (
                (None, None) if cur_rej
                else (assignment[k.name], cur_pred["total_s"])
            )
            for value in k.values:
                if value == assignment[k.name]:
                    continue
                candidate = dict(assignment, **{k.name: value})
                pred, _peak, rej = score(candidate)
                evaluated += 1
                if rej:
                    rejected_memory += 1
                    continue
                total = pred["total_s"]
                if best_total is None or total < best_total:
                    best_value, best_total = value, total
            if best_value is not None and best_value != assignment[k.name]:
                assignment[k.name] = best_value
                changed = True
        if not changed:
            break

    final_pred, final_peak, final_rej = score(assignment)
    if final_rej:
        raise InfeasiblePlan(
            f"every candidate assignment is predicted over the "
            f"{capacity_bytes}-byte device memory capacity "
            f"(smallest predicted peak "
            f"{predict_peak_bytes(mem_model, min(knobs_mod.knob('batch').values))} "
            f"bytes) — raise the capacity or shrink the workload"
        )

    # Explain sweep: score every value of every knob against the FINAL
    # assignment, so `plan explain` renders real alternatives, including
    # the memory-rejected ones.
    knob_report = {}
    for k in knobs_mod.all_knobs():
        values = {}
        for value in k.values:
            pred, peak, rej = score(dict(assignment, **{k.name: value}))
            values[str(value)] = {
                "total_s": None if rej else pred["total_s"],
                **({"predicted_peak_bytes": peak} if peak is not None else {}),
                **({"rejected": "memory"} if rej else {}),
            }
        knob_report[k.name] = {
            "chosen": assignment[k.name],
            "env": k.env,
            "features": list(k.features),
            "pinned": k.name in pinned,
            "values": values,
        }

    return {
        "assignment": assignment,
        "predicted": final_pred,
        "memory": {
            "constraint": "enforced" if mem_model is not None else "off",
            "capacity_bytes": capacity_bytes,
            "predicted_peak_bytes": final_peak,
            "model": mem_model,
        },
        "search": {
            "algorithm": "coordinate-descent",
            "passes": passes,
            "evaluated": evaluated,
            "rejected_memory": rejected_memory,
            "corpus_rows_used": model["rows_used"],
            "knobs": knob_report,
        },
    }

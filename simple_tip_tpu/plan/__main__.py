"""``python -m simple_tip_tpu.plan`` entry point."""

import sys

from simple_tip_tpu.plan.cli import main

if __name__ == "__main__":
    sys.exit(main())

"""The typed knob space the execution planner searches.

Every performance-relevant toggle this repo grew — fit-pool sizing, the
cluster backend, worker platform policy, the fused-chain switch, batch
sizes — is an env var today, set by hand per study. This module is the
registry that makes that space searchable: each :class:`Knob` declares its
env var, its **legal values** (the planner never invents a value a
consumer would reject), its default, and **which cost-model features it
moves** (``obs/costmodel.py`` fits ``[1, cpu?, log1p(count),
log1p(batch), log(group)]`` per phase, divided by workers) — so
``plan/search.py``
knows which knobs the learned model can actually distinguish and which it
scores identically (those keep their default, and ``plan explain`` says
so instead of pretending the model had an opinion).

The registry is also the contract behind the ``hardcoded-knob`` tiplint
rule: library code must not write these env vars into ``os.environ``
directly — a hardcoded knob is invisible to the planner, to ``plan
explain`` and to the plan-vs-actual audit. Scripts and tests stay exempt
(they are entry points / harnesses, exactly where pinning is legitimate).

Stdlib-only: the planner runs in the dependency-free tier-0 CI gate.
"""

from typing import Dict, Iterable, Optional, Tuple

#: Cost-model feature names a knob may move (see ``costmodel._features``
#: plus the ``workers`` divisor in ``predict_study``).
FEATURES = ("platform", "batch", "workers", "group")


class Knob:
    """One tunable: env var, legal values, and its cost-model effect.

    ``param`` names the prediction parameter the value maps onto
    identically (``workers`` / ``batch``); ``effects`` maps specific
    values to prediction-parameter overrides (e.g. ``worker_platforms:
    cpu -> {"platform": "cpu"}``). A knob with neither moves no feature
    the model fits: the search keeps its default and the plan records
    that honestly.
    """

    __slots__ = ("name", "env", "values", "default", "features", "param",
                 "effects", "doc")

    def __init__(self, name: str, env: str, values: Tuple, default,
                 doc: str, features: Tuple[str, ...] = (),
                 param: Optional[str] = None, effects: Optional[dict] = None):
        if default not in values:
            raise ValueError(f"knob {name}: default {default!r} not legal")
        for f in features:
            if f not in FEATURES:
                raise ValueError(f"knob {name}: unknown feature {f!r}")
        self.name = name
        self.env = env
        self.values = tuple(values)
        self.default = default
        self.features = tuple(features)
        self.param = param
        self.effects = dict(effects or {})
        self.doc = doc

    def legal(self, value) -> bool:
        """Whether ``value`` is one of this knob's declared legal values."""
        return value in self.values

    def prediction_overrides(self, value) -> dict:
        """Cost-model parameter overrides this knob value implies."""
        out = dict(self.effects.get(value, {}))
        if self.param is not None:
            out[self.param] = value
        return out

    def coerce(self, raw: str):
        """Parse a CLI/env string into this knob's typed legal value.

        Raises ``ValueError`` (naming the legal values) on anything else —
        the planner never silently accepts a value a consumer would
        reject at launch time.
        """
        for v in self.values:
            if str(v) == str(raw).strip():
                return v
        raise ValueError(
            f"knob {self.name}: {raw!r} is not legal "
            f"(legal: {', '.join(str(v) for v in self.values)})"
        )


#: The knob space, in the deterministic order the search walks it.
KNOBS: Tuple[Knob, ...] = (
    Knob(
        "batch", "TIP_PLAN_BATCH", (2048, 4096, 8192, 16384, 32768), 8192,
        doc="scoring/bench batch size quoted to consumers; moves the cost "
            "model's log1p(batch) feature and the device-memory constraint",
        features=("batch",), param="batch",
    ),
    Knob(
        "cluster_backend", "TIP_CLUSTER_BACKEND",
        ("auto", "jax", "sklearn"), "auto",
        doc="KMeans/GMM backend for the SA fits (ops/surprise.py); "
            "'sklearn' pins the fits to host CPU",
        features=("platform",), effects={"sklearn": {"platform": "cpu"}},
    ),
    Knob(
        "fused_chain", "TIP_FUSED_CHAIN", ("0", "1"), "0",
        doc="whole-chain fused AOT run programs (engine/run_program.py); "
            "indistinguishable to the current cost-model features, so the "
            "default is kept unless pinned",
    ),
    Knob(
        "group_size", "TIP_CHAIN_GROUP", (1, 2, 4, 8), 1,
        doc="cross-run dispatch fusion: models scored per chain dispatch "
            "(engine/run_program.GroupChainRunner; effective only with "
            "fused_chain on); moves the cost model's log(group) feature "
            "and adds ~G x param-bytes stacked-weights residency to the "
            "device-memory constraint",
        features=("group",), param="group",
    ),
    Knob(
        "max_badge", "TIP_SERVE_MAX_BADGE", (256, 512, 1024, 2048), 2048,
        doc="serving badge size bound (serving/knobs.py); the admission "
            "backlog bound divides by it",
    ),
    Knob(
        "sa_fanout", "TIP_SA_FANOUT", ("auto", "1", "0"), "auto",
        doc="whole-variant SA fit fan-out (engine/sa_prep.py)",
    ),
    Knob(
        "sa_mem_frac", "TIP_SA_MEM_FRAC", ("0.25", "0.5", "0.75"), "0.5",
        doc="fraction of available host RAM the SA FitPool fan-out may "
            "budget (engine/sa_prep.fanout_workers)",
    ),
    Knob(
        "sa_pool", "TIP_SA_POOL", ("auto", "1", "2", "4", "8"), "auto",
        doc="SA fit-pool process count (engine/sa_prep.pool_size)",
    ),
    Knob(
        "worker_platforms", "TIP_WORKER_PLATFORMS", ("default", "cpu"),
        "default",
        doc="scheduler worker platform policy (parallel/run_scheduler.py); "
            "'cpu' pins every worker off the accelerator",
        features=("platform",), effects={"cpu": {"platform": "cpu"}},
    ),
    Knob(
        "workers", "TIP_NUM_WORKERS", (1, 2, 4, 8), 1,
        doc="per-host scheduler worker processes; divides every per-phase "
            "wall-clock prediction (ideal packing)",
        features=("workers",), param="workers",
    ),
)

_BY_NAME: Dict[str, Knob] = {k.name: k for k in KNOBS}


def knob(name: str) -> Knob:
    """The knob named ``name`` (raises ``KeyError`` with the catalogue)."""
    try:
        return _BY_NAME[name]
    except KeyError:
        raise KeyError(
            f"unknown knob {name!r} (knobs: {', '.join(sorted(_BY_NAME))})"
        ) from None


def all_knobs() -> Tuple[Knob, ...]:
    """Every registered knob, in search order."""
    return KNOBS


def planned_env_vars() -> frozenset:
    """Env vars owned by the plan/knobs registry.

    The ``hardcoded-knob`` tiplint rule flags library code writing any of
    these into ``os.environ`` directly: tuning decisions must flow through
    an ExecutionPlan (or an operator's shell), never a code-level pin.
    """
    return frozenset(k.env for k in KNOBS)


_BY_ENV: Dict[str, Knob] = {k.env: k for k in KNOBS}


def knob_for_env(env: str) -> Optional[Knob]:
    """The registry knob owning env var ``env``, or None.

    The tiplint dataflow rules consume this export: ``knob-contract``
    treats a ``TIP_*`` read as declared exactly when this returns a knob
    (or the name is in the rule's documented non-planner allowlist), and
    ``hardcoded-knob`` names the owning knob in its finding message.
    """
    return _BY_ENV.get(env)


def default_assignment() -> Dict[str, object]:
    """The all-defaults knob assignment (the search's starting point)."""
    return {k.name: k.default for k in KNOBS}


def validate_assignment(assignment: dict) -> Dict[str, object]:
    """Check names and values against the registry; returns a sorted copy.

    Raises ``ValueError`` naming the first offense — a plan carrying an
    illegal value must fail at load/build time, not at consumer-launch
    time.
    """
    out = {}
    for name in sorted(assignment):
        k = knob(name)  # KeyError -> caller surfaces the catalogue
        value = assignment[name]
        if not k.legal(value):
            raise ValueError(
                f"knob {name}: {value!r} is not legal "
                f"(legal: {', '.join(str(v) for v in k.values)})"
            )
        out[name] = value
    return out


def assignment_env(assignment: dict) -> Dict[str, str]:
    """The env-var view of ``assignment`` (what ``plan apply`` exports)."""
    return {
        knob(name).env: str(value)
        for name, value in sorted(validate_assignment(assignment).items())
    }


def prediction_params(assignment: dict, platform=None) -> dict:
    """Fold ``assignment`` into cost-model prediction parameters.

    Starts from the study's target ``platform`` (None = the default
    backend), workers=1, batch=None, then applies each knob's declared
    overrides in knob order — the single mapping both the search scorer
    and ``plan explain`` use, so a plan's stored predictions are exactly
    what scoring saw.
    """
    params = {"platform": platform, "workers": 1, "batch": None, "group": 1}
    for k in KNOBS:
        if k.name in assignment:
            params.update(k.prediction_overrides(assignment[k.name]))
    return params

"""The versioned ExecutionPlan artifact and its consumer-side readers.

A plan is a schema-stamped JSON document: the chosen knob assignment, the
per-phase predicted seconds the search scored it with, the memory
constraint it was checked against, and a content fingerprint (``plan_id``)
computed over all of that. Two invariants make it a control input rather
than a report:

- **deterministic bytes**: ``to_json`` is canonical (sorted keys, fixed
  indent, no timestamps — the fingerprint covers content only), so the
  same corpus and arguments produce a byte-identical file and CI can
  assert determinism with ``cmp``;
- **failure-safe consumption**: every consumer hook (``active_plan``,
  ``phase_estimate``, ``active_plan_id``) returns None/``"unplanned"`` on
  ANY problem — a missing or corrupt plan file must never block a launch,
  exactly like the advisory cost model it wraps.

Consumers: ``run_scheduler`` (per-phase predicted_s + plan stamp on the
``scheduler.phase`` span), ``scripts/full_study.py`` (assignment applied,
plan stamped into the study root span so ``obs audit`` grades
plan-vs-actual), ``serving/admission.py`` (backlog bound), ``parallel/
fleet.py`` (straggler speculation) and ``bench.py`` (record stamp).
"""

import hashlib
import json
import os

from simple_tip_tpu.plan import knobs as knobs_mod

#: Plan-document schema version. Bump when field semantics change;
#: ``validate`` rejects stamps it does not understand.
SCHEMA = 1

#: Env var naming the active plan file consumers read.
PLAN_FILE_ENV = "TIP_PLAN_FILE"

#: The plan stamp consumers use when no plan is active.
UNPLANNED = "unplanned"


class PlanError(ValueError):
    """A plan document that fails schema/registry validation."""


def _canonical(doc: dict) -> str:
    """The canonical JSON bytes of ``doc`` (fingerprint + file format)."""
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"


def fingerprint(body: dict) -> str:
    """``ep-<12 hex>`` content fingerprint over a plan body (no plan_id)."""
    digest = hashlib.sha256(
        json.dumps(body, sort_keys=True).encode("utf-8")
    ).hexdigest()
    return f"ep-{digest[:12]}"


def build(assignment: dict, predicted: dict, request: dict,
          memory: dict, search: dict) -> dict:
    """Assemble a validated plan document and stamp its ``plan_id``.

    ``predicted`` is the ``costmodel.predict_study`` result for the chosen
    assignment; ``request`` records what was asked (phases/runs/
    case_studies/platform); ``memory`` the capacity constraint outcome;
    ``search`` the per-knob scores ``plan explain`` renders.
    """
    body = {
        "schema": SCHEMA,
        "assignment": knobs_mod.validate_assignment(assignment),
        "request": dict(request),
        "predicted": dict(predicted),
        "memory": dict(memory),
        "search": dict(search),
    }
    body["plan_id"] = fingerprint(
        {k: v for k, v in body.items() if k != "plan_id"}
    )
    return validate(body)


def validate(doc) -> dict:
    """Schema + knob-registry validation; returns ``doc`` or raises
    :class:`PlanError` naming the offense."""
    if not isinstance(doc, dict):
        raise PlanError("plan document is not a JSON object")
    if doc.get("schema") != SCHEMA:
        raise PlanError(
            f"plan schema {doc.get('schema')!r} not understood "
            f"(this reader speaks schema {SCHEMA})"
        )
    for field in ("plan_id", "assignment", "request", "predicted",
                  "memory", "search"):
        if field not in doc:
            raise PlanError(f"plan missing required field {field!r}")
    try:
        knobs_mod.validate_assignment(doc["assignment"])
    except (KeyError, ValueError) as e:
        raise PlanError(f"plan assignment rejected: {e}") from None
    expected = fingerprint({k: v for k, v in doc.items() if k != "plan_id"})
    if doc["plan_id"] != expected:
        raise PlanError(
            f"plan_id {doc['plan_id']!r} does not match content "
            f"fingerprint {expected!r} (edited by hand? re-run suggest)"
        )
    return doc


def to_json(doc: dict) -> str:
    """The plan as canonical JSON text (deterministic bytes)."""
    return _canonical(doc)


def save(doc: dict, path: str) -> str:
    """Validate and atomically write ``doc`` to ``path``; returns ``path``."""
    validate(doc)
    path = os.path.abspath(path)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(to_json(doc))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load(path: str) -> dict:
    """Read and validate the plan at ``path`` (raises :class:`PlanError`)."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise PlanError(f"cannot read plan {path}: {e}") from None
    except ValueError as e:
        raise PlanError(f"plan {path} is not valid JSON: {e}") from None
    return validate(doc)


# -- consumer-side readers (failure-safe by contract) -----------------------

#: ``(abspath, mtime, size) -> doc`` cache: consumers call these per
#: phase/request; the plan file must not be re-read and re-validated
#: every time.
_active_cache: dict = {}


def active_plan():
    """The validated plan named by ``TIP_PLAN_FILE``, or None.

    Failure-safe: unset/missing/corrupt/stale-schema all return None —
    plans are advisory control inputs, never launch blockers.
    """
    raw = os.environ.get(PLAN_FILE_ENV, "").strip()
    if not raw:
        return None
    path = os.path.abspath(raw)
    try:
        st = os.stat(path)
        key = (path, st.st_mtime_ns, st.st_size)
        if key not in _active_cache:
            _active_cache.clear()  # one live plan at a time; no unbounded growth
            _active_cache[key] = load(path)
        return _active_cache[key]
    except (OSError, PlanError):
        return None


def active_plan_id() -> str:
    """The active plan's id, or ``"unplanned"``.

    Stamped into bench records, scheduler phase spans, and (obs v5) every
    incident the alert evaluator opens — a page under a fresh plan points
    at the plan first (RUNBOOK §11).
    """
    doc = active_plan()
    return doc["plan_id"] if doc else UNPLANNED


def phase_estimate(phase: str, n_runs: int = 1, workers: int = 1):
    """The active plan's estimate for ``phase`` scaled to this launch.

    Scales the plan's stored per-run seconds to ``n_runs`` across
    ``workers`` (same ideal-packing arithmetic as
    ``costmodel.predict_study``). Returns ``{predicted_s, error_s, basis:
    "plan", plan_id, corpus_rows}`` or None when no plan is active, the
    phase is not in the plan, or the plan has no usable number — callers
    fall back to the live cost model.
    """
    doc = active_plan()
    if doc is None:
        return None
    info = (doc.get("predicted") or {}).get("by_phase", {}).get(phase)
    if not isinstance(info, dict):
        return None
    per_run = info.get("per_run_s")
    if not isinstance(per_run, (int, float)):
        return None
    scale = max(int(n_runs), 1) / max(int(workers), 1)
    per_err = info.get("error_s")
    planned_runs = max(int((doc.get("predicted") or {}).get("runs") or 1), 1)
    planned_workers = max(int((doc.get("predicted") or {}).get("workers") or 1), 1)
    # error_s in the plan is study-total; recover the per-run error before
    # rescaling so a 1-run phase does not inherit a 400-run error bar.
    per_run_err = (
        float(per_err) * planned_workers / planned_runs
        if isinstance(per_err, (int, float)) else 0.0
    )
    return {
        "predicted_s": round(float(per_run) * scale, 4),
        "error_s": round(per_run_err * scale, 4),
        "basis": "plan",
        "plan_id": doc["plan_id"],
        "corpus_rows": info.get("corpus_rows"),
    }

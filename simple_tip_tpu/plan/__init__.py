"""Self-tuning execution planner: knob space, search, ExecutionPlan.

The consumer-side readers are re-exported here so call sites stay one
cheap import: ``from simple_tip_tpu import plan; plan.phase_estimate(...)``.
Everything in this package is stdlib-only — it runs in the dependency-free
tier-0 CI gate.
"""

from simple_tip_tpu.plan.plan import (  # noqa: F401
    PLAN_FILE_ENV,
    UNPLANNED,
    PlanError,
    active_plan,
    active_plan_id,
    phase_estimate,
)

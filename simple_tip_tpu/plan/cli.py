"""``python -m simple_tip_tpu.plan`` — suggest / explain / apply.

The operator surface of the planner. Exit codes follow the obs CLI
contract exactly:

- 0: plan produced / rendered / applied;
- 2: bad input (unknown knob, illegal value, unparseable plan, every
  candidate over the memory capacity);
- 3: insufficient corpus — a skip, not a failure, mirroring ``obs
  predict``/``obs trend``. Under ``--json`` stdout STILL carries one
  valid JSON document on the exit-3 path (diagnostics go to stderr), so
  piped consumers never parse an empty body.

``suggest`` writes deterministic bytes (same corpus + same arguments =>
byte-identical plan file): CI asserts that with ``cmp``, and the plan_id
fingerprint makes any hand edit loudly invalid.
"""

import argparse
import json
import os
import sys

#: Env var supplying the default memory capacity bound for ``suggest``.
MEM_ENV = "TIP_PLAN_MEM_BYTES"

_SUFFIX = {"k": 1024, "m": 1024 ** 2, "g": 1024 ** 3}


def parse_bytes(raw: str) -> int:
    """``"512m"``/``"8g"``/``"1073741824"`` -> bytes (ValueError otherwise)."""
    text = str(raw).strip().lower()
    if not text:
        raise ValueError("empty byte count")
    mult = 1
    if text[-1] in _SUFFIX:
        mult = _SUFFIX[text[-1]]
        text = text[:-1]
    return int(float(text) * mult)


def _capacity_bytes(args):
    """The capacity bound from ``--mem-bytes`` or ``TIP_PLAN_MEM_BYTES``."""
    raw = args.mem_bytes or os.environ.get(MEM_ENV, "").strip()
    if not raw:
        return None
    try:
        return parse_bytes(raw)
    except ValueError:
        raise ValueError(
            f"memory capacity {raw!r} is not a byte count "
            "(plain bytes or k/m/g suffix)"
        ) from None


def _pins(specs):
    """``["batch=4096", ...]`` -> a typed, registry-validated assignment."""
    from simple_tip_tpu.plan import knobs as knobs_mod

    pinned = {}
    for spec in specs or []:
        name, sep, raw = spec.partition("=")
        if not sep:
            raise ValueError(f"--set wants knob=value, got {spec!r}")
        pinned[name.strip()] = knobs_mod.knob(name.strip()).coerce(raw)
    return pinned


def render_plan(doc: dict) -> str:
    """One plan as a deterministic text summary (the ``suggest`` view)."""
    from simple_tip_tpu.obs import costmodel

    req = doc["request"]
    out = [
        f"plan {doc['plan_id']} (schema {doc['schema']})",
        f"  request: phases={','.join(req['phases'])} runs={req['runs']} "
        f"case_studies={req['case_studies']} "
        f"platform={req['platform'] or 'default'}",
        "  assignment:",
    ]
    knobs = doc["search"]["knobs"]
    for name, value in sorted(doc["assignment"].items()):
        info = knobs.get(name, {})
        tag = " (pinned)" if info.get("pinned") else ""
        out.append(f"    {name:<18} = {value!s:<8} [{info.get('env', '?')}]{tag}")
    mem = doc["memory"]
    if mem["constraint"] == "enforced":
        out.append(
            f"  memory: predicted peak {mem['predicted_peak_bytes']} bytes "
            f"within capacity {mem['capacity_bytes']} "
            f"({doc['search']['rejected_memory']} candidate(s) rejected)"
        )
    else:
        out.append(
            "  memory: constraint off (no --mem-bytes / TIP_PLAN_MEM_BYTES)"
        )
    out.append("")
    out.append(costmodel.render_prediction(doc["predicted"]))
    return "\n".join(out)


def render_explain(doc: dict) -> str:
    """Per-knob alternatives table (the ``explain`` view)."""
    out = [
        f"plan {doc['plan_id']} — why each knob landed where it did",
        "",
        f"  {'knob':<18} {'value':>8} {'predicted s':>12}  verdict",
    ]
    for name, info in sorted(doc["search"]["knobs"].items()):
        moved = ",".join(info["features"]) or "none"
        for raw_value, entry in sorted(
            info["values"].items(),
            key=lambda kv: list(info["values"]).index(kv[0]),
        ):
            chosen = raw_value == str(info["chosen"])
            if entry.get("rejected"):
                verdict = "REJECTED: over memory capacity"
            elif chosen and info.get("pinned"):
                verdict = "chosen (pinned by operator)"
            elif chosen:
                verdict = "chosen"
            else:
                verdict = ""
            total = entry.get("total_s")
            out.append(
                f"  {name:<18} {raw_value:>8} "
                f"{(f'{total:.1f}' if total is not None else '-'):>12}  "
                f"{verdict}"
            )
        out.append(f"  {'':<18} {'':>8} {'':>12}  (moves: {moved})")
    out.append("")
    out.append(
        "knobs that move no cost-model feature keep their default: the "
        "model cannot rank their values, and the planner says so instead "
        "of guessing."
    )
    return "\n".join(out)


def _suggest(args) -> int:
    from simple_tip_tpu.obs import store
    from simple_tip_tpu.plan import plan as plan_mod
    from simple_tip_tpu.plan import search as search_mod

    phases = [p.strip() for p in args.phases.split(",") if p.strip()]
    if not phases:
        print("plan suggest: --phases must name at least one phase",
              file=sys.stderr)
        return 2
    try:
        capacity = _capacity_bytes(args)
        pinned = _pins(args.set)
    except (KeyError, ValueError) as e:
        print(f"plan suggest: {e}", file=sys.stderr)
        return 2
    rows = store.load_corpus(args.index or store.default_index_dir())

    def _exit3(reason: str) -> int:
        if args.json:
            print(json.dumps(
                {"ok": False, "error": "insufficient_corpus",
                 "reason": reason, "plan_id": None},
                indent=2, sort_keys=True,
            ))
        print(
            f"plan suggest: INSUFFICIENT CORPUS — {reason} (exit 3)",
            file=sys.stderr,
        )
        return 3

    if not rows:
        return _exit3(
            "the feature-store index is empty — run "
            "`python -m simple_tip_tpu.obs runs <roots>` first"
        )
    try:
        result = search_mod.search(
            rows, phases, runs=args.runs, case_studies=args.case_studies,
            platform=args.platform, capacity_bytes=capacity, pinned=pinned,
        )
    except search_mod.InsufficientCorpus as e:
        return _exit3(str(e))
    except search_mod.InfeasiblePlan as e:
        print(f"plan suggest: {e}", file=sys.stderr)
        return 2
    doc = plan_mod.build(
        assignment=result["assignment"],
        predicted=result["predicted"],
        request={
            "phases": phases,
            "runs": args.runs,
            "case_studies": args.case_studies,
            "platform": args.platform,
        },
        memory=result["memory"],
        search=result["search"],
    )
    if args.out:
        path = plan_mod.save(doc, args.out)
        print(f"plan {doc['plan_id']} -> {path}", file=sys.stderr)
    if args.json:
        sys.stdout.write(plan_mod.to_json(doc))
    else:
        print(render_plan(doc))
    return 0


def _load_target(target):
    """The plan doc for ``explain``: an explicit path or the active plan."""
    from simple_tip_tpu.plan import plan as plan_mod

    if target:
        return plan_mod.load(target)
    doc = plan_mod.active_plan()
    if doc is None:
        raise plan_mod.PlanError(
            "no plan file given and TIP_PLAN_FILE names no readable plan"
        )
    return doc


def _explain(args) -> int:
    from simple_tip_tpu.plan import plan as plan_mod

    try:
        doc = _load_target(args.plan)
    except plan_mod.PlanError as e:
        print(f"plan explain: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(doc["search"], indent=2, sort_keys=True))
    else:
        print(render_explain(doc))
    return 0


def _apply(args) -> int:
    from simple_tip_tpu.plan import knobs as knobs_mod
    from simple_tip_tpu.plan import plan as plan_mod

    try:
        doc = plan_mod.load(args.plan)
    except plan_mod.PlanError as e:
        print(f"plan apply: {e}", file=sys.stderr)
        return 2
    env = knobs_mod.assignment_env(doc["assignment"])
    env[plan_mod.PLAN_FILE_ENV] = os.path.abspath(args.plan)
    if args.command:
        cmd = list(args.command)
        if cmd and cmd[0] == "--":
            cmd = cmd[1:]
        if not cmd:
            print("plan apply: empty command after --", file=sys.stderr)
            return 2
        full_env = dict(os.environ)
        full_env.update(env)
        print(
            f"plan apply: {doc['plan_id']} -> exec {' '.join(cmd)}",
            file=sys.stderr,
        )
        os.execvpe(cmd[0], cmd, full_env)  # no return
    # No command: print shell-sourceable export lines (the override-
    # etiquette path — an operator can edit one line before sourcing).
    for key in sorted(env):
        print(f"export {key}={env[key]}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m simple_tip_tpu.plan",
        description="self-tuning execution planner over the obs feature store",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser(
        "suggest", help="search the knob space, emit an ExecutionPlan"
    )
    s.add_argument("--phases", required=True,
                   help="comma-separated phase names to plan for")
    s.add_argument("--runs", type=int, required=True,
                   help="runs per case study")
    s.add_argument("--case-studies", type=int, default=1)
    s.add_argument("--platform", default=None,
                   help="target platform the study launches on (cpu/tpu)")
    s.add_argument("--index", default=None,
                   help="feature-store index dir (default: obs default)")
    s.add_argument("--mem-bytes", default=None,
                   help=f"device memory capacity bound (k/m/g suffix ok; "
                        f"default ${MEM_ENV}; unset = constraint off)")
    s.add_argument("--set", action="append", metavar="KNOB=VALUE",
                   help="pin a knob (repeatable); pinned knobs skip search")
    s.add_argument("-o", "--out", default=None,
                   help="also write the plan JSON to this path")
    s.add_argument("--json", action="store_true",
                   help="print the plan document instead of the summary")
    s.set_defaults(fn=_suggest)

    e = sub.add_parser(
        "explain", help="render why each knob landed where it did"
    )
    e.add_argument("plan", nargs="?", default=None,
                   help="plan file (default: $TIP_PLAN_FILE)")
    e.add_argument("--json", action="store_true")
    e.set_defaults(fn=_explain)

    a = sub.add_parser(
        "apply",
        help="export the plan's knob env (or exec a command under it)",
    )
    a.add_argument("plan", help="plan file to activate")
    a.add_argument("command", nargs=argparse.REMAINDER,
                   help="optional -- command to exec under the plan env")
    a.set_defaults(fn=_apply)
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
